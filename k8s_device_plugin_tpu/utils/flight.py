"""Flight recorder: a bounded black-box journal of typed events.

PR 1 gave the daemon and the serving engine scrapeable gauges and a
request-span ring — good for "how is it doing NOW".  What they could not
answer is the post-mortem question arXiv:2510.16946 frames as the
host-side diagnosis gap (and that BENCH_r05 actually hit: "accelerator
backend dead or hung" with nothing to dump): *what happened in the last
60 seconds before it went wrong*.  This module is the black box:

- **Typed events**: ``record(kind, **fields)`` appends one timestamped
  dict (registration, ListAndWatch updates, Allocate, health
  transitions, engine step summaries, admission rejects, incidents —
  the catalog lives in docs/operations.md "Forensics").
- **Bounded + drop-accounted**: a ``deque(maxlen=capacity)``; overflow
  evicts the oldest event and counts it, per kind — the snapshot always
  says how much history it is NOT showing.
- **Snapshot-to-JSON**: :meth:`snapshot` is JSON-safe by construction
  (fields are sanitized at record time, never at dump time — a dump
  taken from a signal handler must not be able to fail on a weird
  field).
- **Dump-on-demand**: ``kill -USR2 <pid>`` writes every registered
  recorder to ``TPU_PLUGIN_DUMP_DIR`` (or the system tempdir); an
  atexit hook writes a final dump when a dump dir was explicitly
  configured, so even a crash-exit leaves the last window on disk
  (the DaemonSet/serving yamls mount the dir).

Stdlib-only and cheap enough to leave on: one lock, one deque append,
no I/O until a dump is asked for.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal as _signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Optional

log = logging.getLogger("tpu.flight")

DUMP_DIR_ENV = "TPU_PLUGIN_DUMP_DIR"

_JSON_SCALARS = (str, int, float, bool, type(None))


def _json_safe(value):
    """Coerce one event field to something json.dumps cannot choke on.

    Runs at RECORD time so the dump path (which may run inside a signal
    handler or interpreter teardown) never needs to repr live objects."""
    if isinstance(value, _JSON_SCALARS):
        return value
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    return str(value)


class FlightRecorder:
    """Thread-safe bounded journal of typed events with drop accounting.

    ``name`` keys the recorder in multi-recorder dumps (a serving pod
    has an "engine" box; the plugin daemon a "daemon" box).  The lock is
    reentrant so a SIGUSR2 arriving while the main thread is inside
    :meth:`record` cannot deadlock the dump.
    """

    def __init__(self, capacity: int = 2048, name: str = "flight"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.name = name
        self.capacity = capacity
        self._lock = threading.RLock()
        self._ring: deque[dict] = deque(maxlen=capacity)  # guarded by: _lock
        self.recorded = 0
        self.dropped = 0
        self._dropped_by_kind: dict[str, int] = {}
        self._recorded_by_kind: dict[str, int] = {}

    def record(self, kind: str, **fields) -> dict:
        """Append one typed event; returns the entry (already JSON-safe)."""
        entry = {"ts": round(time.time(), 6), "kind": str(kind)}
        for key, value in fields.items():
            entry[key] = _json_safe(value)
        with self._lock:
            self.recorded += 1
            k = entry["kind"]
            self._recorded_by_kind[k] = self._recorded_by_kind.get(k, 0) + 1
            if len(self._ring) == self.capacity:
                evicted = self._ring[0]
                self.dropped += 1
                ek = evicted.get("kind", "?")
                self._dropped_by_kind[ek] = self._dropped_by_kind.get(ek, 0) + 1
            self._ring.append(entry)
        return entry

    def count(self, kind: str) -> int:
        """Lifetime count of one event kind — survives ring eviction, so
        a scorer can ask "how many admission.shed decisions happened"
        even after a busy window rolled the events themselves out."""
        with self._lock:
            return self._recorded_by_kind.get(kind, 0)

    def window(
        self,
        seconds: Optional[float] = None,
        last: Optional[int] = None,
        kinds=None,
    ) -> list[dict]:
        """Recent events, oldest first — the slice an incident record
        attaches.  ``seconds`` keeps events newer than now-seconds;
        ``last`` caps the count (applied after the other filters);
        ``kinds`` restricts to an iterable of event kinds."""
        with self._lock:
            events = list(self._ring)
        if seconds is not None:
            horizon = time.time() - seconds
            events = [e for e in events if e["ts"] >= horizon]
        if kinds is not None:
            wanted = set(kinds)
            events = [e for e in events if e["kind"] in wanted]
        if last is not None and len(events) > last:
            events = events[-last:]
        return [dict(e) for e in events]

    def snapshot(self) -> dict:
        """The whole box as one JSON-safe dict: events plus the drop
        accounting that says how truncated the window is."""
        with self._lock:
            return {
                "name": self.name,
                "capacity": self.capacity,
                "recorded": self.recorded,
                "dropped": self.dropped,
                "dropped_by_kind": dict(self._dropped_by_kind),
                "recorded_by_kind": dict(self._recorded_by_kind),
                "events": [dict(e) for e in self._ring],
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.recorded = 0
            self.dropped = 0
            self._dropped_by_kind.clear()
            self._recorded_by_kind.clear()


# ---------------------------------------------------------------- dumping

# Recorders that SIGUSR2/atexit dumps cover.  Explicit registration (the
# daemon/server mains call register()) rather than auto-register in
# __init__: tests construct hundreds of throwaway recorders and a global
# dump must not grow with them.
_registry: list[FlightRecorder] = []
# Span rings (utils/spans.SpanRecorder) dumped ALONGSIDE the flight
# recorders: a post-mortem dump then carries both halves of the
# request story — the typed-event journal AND the span trees the trace
# assembler (tools/trace_assemble.py) joins across processes.  Same
# explicit-registration rule.
_span_registry: list = []
_registry_lock = threading.Lock()


def register(recorder: FlightRecorder) -> FlightRecorder:
    """Add a recorder to the process-wide dump set (idempotent)."""
    with _registry_lock:
        if recorder not in _registry:
            _registry.append(recorder)
    return recorder


def unregister(recorder: FlightRecorder) -> None:
    with _registry_lock:
        if recorder in _registry:
            _registry.remove(recorder)


def registered() -> list[FlightRecorder]:
    with _registry_lock:
        return list(_registry)


def register_spans(recorder):
    """Add a span ring (utils/spans.SpanRecorder) to the process-wide
    dump set (idempotent): SIGUSR2/atexit dumps then embed its spans
    under ``spans.<recorder.name>`` — the offline input to
    ``tools/trace_assemble.py``."""
    with _registry_lock:
        if recorder not in _span_registry:
            _span_registry.append(recorder)
    return recorder


def unregister_spans(recorder) -> None:
    with _registry_lock:
        if recorder in _span_registry:
            _span_registry.remove(recorder)


def registered_spans() -> list:
    with _registry_lock:
        return list(_span_registry)


def default_dump_dir(environ=None) -> Optional[str]:
    """The configured dump directory (``TPU_PLUGIN_DUMP_DIR``) or None."""
    environ = os.environ if environ is None else environ
    return environ.get(DUMP_DIR_ENV) or None


# Process-wide dump-dir retention budget (utils/postmortem.py's shared
# LRU sweeper): when armed (the daemons' --dump-budget-mb flag),
# dump_all prunes oldest-first after each write so SIGUSR2/atexit dumps
# and postmortem bundles never accumulate unbounded.
_dump_budget: dict = {"bytes": None, "entries": None}


def set_dump_budget(
    budget_bytes: Optional[int], max_entries: Optional[int] = None
) -> None:
    """Arm (or clear, with None) the dump-dir retention budget applied
    after every dump_all write."""
    _dump_budget["bytes"] = budget_bytes
    _dump_budget["entries"] = max_entries


def dump_all(
    dump_dir: Optional[str] = None,
    reason: str = "manual",
    recorders=None,
    span_recorders=None,
) -> Optional[str]:
    """Write every registered (or explicitly passed) recorder to one JSON
    file under ``dump_dir`` (env default, tempdir fallback); returns the
    path, or None when there was nothing to dump.  Registered span rings
    ride along under ``spans`` (the trace assembler's offline input).
    Never raises — the callers are signal handlers and atexit hooks,
    where an exception would replace the forensic record with a
    traceback."""
    recs = list(recorders) if recorders is not None else registered()
    span_recs = (
        list(span_recorders)
        if span_recorders is not None
        else registered_spans()
    )
    if not recs and not span_recs:
        return None
    payload = {
        "schema": "tpu-flight-dump/v1",
        "reason": reason,
        "pid": os.getpid(),
        "argv": [str(a) for a in sys.argv],
        "ts": round(time.time(), 3),
        "recorders": {r.name: r.snapshot() for r in recs},
    }
    if span_recs:
        payload["spans"] = {r.name: r.dump() for r in span_recs}
    directory = dump_dir or default_dump_dir() or tempfile.gettempdir()
    path = os.path.join(
        directory,
        f"tpu-flight-{os.getpid()}-{reason}-{int(time.time())}.json",
    )
    try:
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, separators=(",", ":"))
        # Atomic publish: a collector tailing the dir never reads a
        # half-written dump.
        os.replace(tmp, path)
    except OSError as e:
        log.error("flight dump to %s failed: %s", path, e)
        return None
    log.info("flight dump (%s) -> %s", reason, path)
    if _dump_budget["bytes"] is not None or _dump_budget["entries"] is not None:
        # Retention sweep (never raises): the just-written dump is
        # protected so a tiny budget cannot eat its own forensics.
        from . import postmortem as _postmortem

        _postmortem.sweep_dump_dir(
            directory,
            _dump_budget["bytes"],
            _dump_budget["entries"],
            protect=(path,),
            flight=recs[0] if recs else None,
        )
    return path


class DumpHandle:
    """Installed dump hooks, with an uninstall for tests/embedders."""

    def __init__(self, prev_handler, signum, atexit_fn):
        self._prev = prev_handler
        self._signum = signum
        self._atexit_fn = atexit_fn

    def uninstall(self) -> None:
        if self._signum is not None:
            try:
                _signal.signal(self._signum, self._prev)
            except (ValueError, OSError):
                pass
            self._signum = None
        if self._atexit_fn is not None:
            atexit.unregister(self._atexit_fn)
            self._atexit_fn = None


def install_dump_handlers(
    dump_dir: Optional[str] = None,
    *,
    signum: int = getattr(_signal, "SIGUSR2", 0),
    on_exit: bool = True,
) -> DumpHandle:
    """Arm the black box: SIGUSR2 dumps every registered recorder on
    demand, and (``on_exit``) an atexit hook writes a final dump WHEN a
    dump dir was configured (argument or ``TPU_PLUGIN_DUMP_DIR``) —
    unconfigured processes must not litter tempdirs on every clean exit.

    Signal installation is skipped quietly off the main thread (hermetic
    tests drive daemon mains from worker threads); the atexit hook still
    arms.  Returns a handle whose ``uninstall()`` restores the previous
    signal disposition."""
    prev = None
    installed_signum = None
    if signum:
        def _on_signal(_signum, _frame):
            dump_all(dump_dir, reason="sigusr2")

        try:
            prev = _signal.signal(signum, _on_signal)
            installed_signum = signum
        except ValueError:
            log.debug("not on main thread; skipping SIGUSR2 dump handler")
    atexit_fn = None
    if on_exit and (dump_dir or default_dump_dir()):
        def _on_exit():
            dump_all(dump_dir, reason="exit")

        atexit.register(_on_exit)
        atexit_fn = _on_exit
    return DumpHandle(prev, installed_signum, atexit_fn)
