"""Hand-authored message classes for the kubelet PodResources API (v1).

Same protoc-free constraint as the device-plugin contract (see api.py):
grpcio is installed without grpcio-tools, so there is no protoc to run.
``deviceplugin_pb2.py`` vendors a protoc-generated serialized descriptor;
for this second proto we go one step further and build the
``FileDescriptorProto`` programmatically at import time — every field
number and type below is the wire contract and must match
``k8s.io/kubelet/pkg/apis/podresources/v1/api.proto`` exactly
(podresources.proto in this directory carries the readable definition).

The DRA messages (``DynamicResource`` et al., ``ContainerResources``
field 5) are intentionally not declared: proto3 parsers skip unknown
fields, so a real kubelet that streams them still interoperates, and the
plugin only attributes device-plugin-managed resources.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2 as _dpb
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf.internal import builder as _builder

_F = _dpb.FieldDescriptorProto

_fdp = _dpb.FileDescriptorProto(
    name="podresources.proto", package="v1", syntax="proto3"
)


def _field(name, number, type_, label=_F.LABEL_OPTIONAL, type_name=None):
    f = _F(name=name, number=number, type=type_, label=label)
    if type_name is not None:
        f.type_name = type_name
    return f


def _message(name, *fields):
    m = _fdp.message_type.add(name=name)
    m.field.extend(fields)


_message("AllocatableResourcesRequest")
_message(
    "AllocatableResourcesResponse",
    _field("devices", 1, _F.TYPE_MESSAGE, _F.LABEL_REPEATED, ".v1.ContainerDevices"),
    _field("cpu_ids", 2, _F.TYPE_INT64, _F.LABEL_REPEATED),
    _field("memory", 3, _F.TYPE_MESSAGE, _F.LABEL_REPEATED, ".v1.ContainerMemory"),
)
_message("ListPodResourcesRequest")
_message(
    "ListPodResourcesResponse",
    _field("pod_resources", 1, _F.TYPE_MESSAGE, _F.LABEL_REPEATED, ".v1.PodResources"),
)
_message(
    "PodResources",
    _field("name", 1, _F.TYPE_STRING),
    _field("namespace", 2, _F.TYPE_STRING),
    _field("containers", 3, _F.TYPE_MESSAGE, _F.LABEL_REPEATED, ".v1.ContainerResources"),
)
_message(
    "ContainerResources",
    _field("name", 1, _F.TYPE_STRING),
    _field("devices", 2, _F.TYPE_MESSAGE, _F.LABEL_REPEATED, ".v1.ContainerDevices"),
    _field("cpu_ids", 3, _F.TYPE_INT64, _F.LABEL_REPEATED),
    _field("memory", 4, _F.TYPE_MESSAGE, _F.LABEL_REPEATED, ".v1.ContainerMemory"),
)
_message(
    "ContainerMemory",
    _field("memory_type", 1, _F.TYPE_STRING),
    _field("size", 2, _F.TYPE_UINT64),
    _field("topology", 3, _F.TYPE_MESSAGE, type_name=".v1.TopologyInfo"),
)
_message(
    "ContainerDevices",
    _field("resource_name", 1, _F.TYPE_STRING),
    _field("device_ids", 2, _F.TYPE_STRING, _F.LABEL_REPEATED),
    _field("topology", 3, _F.TYPE_MESSAGE, type_name=".v1.TopologyInfo"),
)
_message(
    "TopologyInfo",
    _field("nodes", 1, _F.TYPE_MESSAGE, _F.LABEL_REPEATED, ".v1.NUMANode"),
)
_message("NUMANode", _field("ID", 1, _F.TYPE_INT64))
_message(
    "GetPodResourcesRequest",
    _field("pod_name", 1, _F.TYPE_STRING),
    _field("pod_namespace", 2, _F.TYPE_STRING),
)
_message(
    "GetPodResourcesResponse",
    _field("pod_resources", 1, _F.TYPE_MESSAGE, type_name=".v1.PodResources"),
)

_svc = _fdp.service.add(name="PodResourcesLister")
_svc.method.add(
    name="List",
    input_type=".v1.ListPodResourcesRequest",
    output_type=".v1.ListPodResourcesResponse",
)
_svc.method.add(
    name="GetAllocatableResources",
    input_type=".v1.AllocatableResourcesRequest",
    output_type=".v1.AllocatableResourcesResponse",
)
_svc.method.add(
    name="Get",
    input_type=".v1.GetPodResourcesRequest",
    output_type=".v1.GetPodResourcesResponse",
)

DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(_fdp.SerializeToString())
_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, "podresources_pb2", globals())
