"""Hand-written gRPC bindings for the v1beta1 device-plugin contract.

grpcio is installed without grpcio-tools in this environment, so instead of
protoc-generated service stubs we bind the (protoc-generated) message classes
to gRPC method paths ourselves.  The method paths are fixed by the proto
package/service/method names and match what the kubelet dials/serves
(reference wire contract: vendored deviceplugin/v1beta1/api.proto:23-67 and
its generated api.pb.go bindings).

Works with both `grpc` (sync) and `grpc.aio` channels/servers: generic
handlers are accepted by both server flavors, and `channel.unary_unary`/
`unary_stream` exist on both channel flavors.
"""

from __future__ import annotations

import grpc

from . import deviceplugin_pb2 as pb
from . import podresources_pb2 as prpb
from .constants import (
    DEVICE_PLUGIN_SERVICE,
    POD_RESOURCES_SERVICE,
    REGISTRATION_SERVICE,
)

__all__ = [
    "pb",
    "prpb",
    "RegistrationStub",
    "DevicePluginStub",
    "PodResourcesListerStub",
    "add_registration_servicer",
    "add_device_plugin_servicer",
    "add_pod_resources_servicer",
]


class RegistrationStub:
    """Client for the kubelet's Registration service (plugin -> kubelet)."""

    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            f"/{REGISTRATION_SERVICE}/Register",
            request_serializer=pb.RegisterRequest.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )


class DevicePluginStub:
    """Client for a plugin's DevicePlugin service (kubelet -> plugin).

    Used by our hermetic fake kubelet in tests; a real kubelet holds the
    equivalent generated client.
    """

    def __init__(self, channel: grpc.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/GetDevicePluginOptions",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            f"/{DEVICE_PLUGIN_SERVICE}/ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/GetPreferredAllocation",
            request_serializer=pb.PreferredAllocationRequest.SerializeToString,
            response_deserializer=pb.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            f"/{DEVICE_PLUGIN_SERVICE}/PreStartContainer",
            request_serializer=pb.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb.PreStartContainerResponse.FromString,
        )


class PodResourcesListerStub:
    """Client for the kubelet's PodResourcesLister service (plugin -> kubelet).

    Dialed on ``pod-resources/kubelet.sock`` (constants.POD_RESOURCES_SOCKET)
    by plugin/attribution.py; the hermetic FakeKubelet serves the same
    service in tests.
    """

    def __init__(self, channel: grpc.Channel):
        self.List = channel.unary_unary(
            f"/{POD_RESOURCES_SERVICE}/List",
            request_serializer=prpb.ListPodResourcesRequest.SerializeToString,
            response_deserializer=prpb.ListPodResourcesResponse.FromString,
        )
        self.GetAllocatableResources = channel.unary_unary(
            f"/{POD_RESOURCES_SERVICE}/GetAllocatableResources",
            request_serializer=prpb.AllocatableResourcesRequest.SerializeToString,
            response_deserializer=prpb.AllocatableResourcesResponse.FromString,
        )
        self.Get = channel.unary_unary(
            f"/{POD_RESOURCES_SERVICE}/Get",
            request_serializer=prpb.GetPodResourcesRequest.SerializeToString,
            response_deserializer=prpb.GetPodResourcesResponse.FromString,
        )


def add_registration_servicer(servicer, server) -> None:
    """Register a Registration servicer (an object with .Register) on a server."""
    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb.RegisterRequest.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(REGISTRATION_SERVICE, handlers),)
    )


def add_device_plugin_servicer(servicer, server) -> None:
    """Register a DevicePlugin servicer on a server.

    `servicer` provides GetDevicePluginOptions, ListAndWatch (server-streaming),
    Allocate, and PreStartContainer.
    """
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb.PreferredAllocationRequest.FromString,
            response_serializer=pb.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb.AllocateRequest.FromString,
            response_serializer=pb.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb.PreStartContainerRequest.FromString,
            response_serializer=pb.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(DEVICE_PLUGIN_SERVICE, handlers),)
    )


def add_pod_resources_servicer(servicer, server) -> None:
    """Register a PodResourcesLister servicer (List, GetAllocatableResources,
    Get) on a server — what the hermetic FakeKubelet uses to stand in for
    the real kubelet's pod-resources endpoint."""
    handlers = {
        "List": grpc.unary_unary_rpc_method_handler(
            servicer.List,
            request_deserializer=prpb.ListPodResourcesRequest.FromString,
            response_serializer=prpb.ListPodResourcesResponse.SerializeToString,
        ),
        "GetAllocatableResources": grpc.unary_unary_rpc_method_handler(
            servicer.GetAllocatableResources,
            request_deserializer=prpb.AllocatableResourcesRequest.FromString,
            response_serializer=prpb.AllocatableResourcesResponse.SerializeToString,
        ),
        "Get": grpc.unary_unary_rpc_method_handler(
            servicer.Get,
            request_deserializer=prpb.GetPodResourcesRequest.FromString,
            response_serializer=prpb.GetPodResourcesResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(POD_RESOURCES_SERVICE, handlers),)
    )
