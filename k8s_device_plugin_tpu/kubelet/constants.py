"""Protocol constants for the kubelet device-plugin API (v1beta1).

Mirrors the contract constants the kubelet hard-codes (reference analogue:
vendored deviceplugin/v1beta1/constants.go:19-35).  These values are part of
the kubelet's public API surface and must match exactly.
"""

# Device health states streamed in ListAndWatchResponse.
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

# API version announced in RegisterRequest.version.
VERSION = "v1beta1"

# Directory in which the kubelet serves kubelet.sock and expects plugin
# sockets to appear.
DEVICE_PLUGIN_PATH = "/var/lib/kubelet/device-plugins/"

# The kubelet's own Registration socket.
KUBELET_SOCKET_NAME = "kubelet.sock"
KUBELET_SOCKET = DEVICE_PLUGIN_PATH + KUBELET_SOCKET_NAME

# Upper bound the kubelet applies to a PreStartContainer RPC.
PRE_START_CONTAINER_TIMEOUT_SECONDS = 30

# gRPC method paths, fixed by the proto package/service/method names.
REGISTRATION_SERVICE = "v1beta1.Registration"
DEVICE_PLUGIN_SERVICE = "v1beta1.DevicePlugin"

# The kubelet's PodResources introspection endpoint (podresources/v1):
# which pod/container currently holds which device IDs — the kubelet-truth
# side of the plugin's allocation-reconciliation audit.
POD_RESOURCES_PATH = "/var/lib/kubelet/pod-resources/"
POD_RESOURCES_SOCKET_NAME = "kubelet.sock"
POD_RESOURCES_SOCKET = POD_RESOURCES_PATH + POD_RESOURCES_SOCKET_NAME
POD_RESOURCES_SERVICE = "v1.PodResourcesLister"
