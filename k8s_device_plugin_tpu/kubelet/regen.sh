#!/bin/sh
# Regenerate deviceplugin_pb2.py from the hand-authored proto.
cd "$(dirname "$0")" && exec protoc --python_out=. deviceplugin.proto
