"""Explicit sharding contract for the serving-engine state dict.

Tensor-parallel serving used to work only by accident: sharded params
propagated THROUGH the jitted paged-decode step, but nothing placed the
engine's own state — KV pools, page tables, the device-resident step
dict — so every ``_dev=None`` rebuild re-derived placement and a
multi-MB pool could silently end up replicated on every chip.  This
module is the contract: one spec per state-dict leaf, applied at engine
construction and on every rebuild, plus a coverage lint that refuses
silent replication.

The layout (mirrors parallel/tensor.py's Megatron split):

- ``params`` — tensor.tp_param_sharding (heads/ffn/vocab over ``tp``);
- ``pool_key`` / ``pool_value``  [num_pages, page_size, kv_heads, head_dim]
  — kv-heads axis over ``tp`` (each chip holds its head group's pages:
  the paged append writes and the attention reads stay chip-local, the
  only cross-chip traffic is the per-block attention-out all-reduce XLA
  already inserts for the params);
- ``pool_key_scale`` / ``pool_value_scale``  [num_pages, page_size,
  kv_heads] (quant_kv) — kv-heads axis over ``tp``, riding their pools;
- ``page_table`` / ``seq_lens`` / the chain — replicated (host-truth
  indices every chip needs whole);
- the device-resident step dict (tokens/positions/temps/aids/key,
  filters/biases) — replicated (tiny per-slot vectors).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .tensor import _path_str

# Leaf name -> which dimension carries kv heads.  Pools are 4-d
# [pages, page_size, kv_heads, head_dim]; scale pools (quant_kv) 3-d
# [pages, page_size, kv_heads].
_POOL_KV_DIM = {
    "pool_key": 2,
    "pool_value": 2,
    "pool_key_scale": 2,
    "pool_value_scale": 2,
}


def cache_leaf_spec(path_str: str, leaf: Any, tp: int, tp_axis: str = "tp") -> P:
    """PartitionSpec for one paged-cache leaf by name.

    Pools shard their kv-heads dimension over ``tp``; everything else
    (page tables, seq_lens) replicates.  A pool whose kv-heads dimension
    ``tp`` does not divide raises — a silently replicated pool is
    exactly the failure mode this contract exists to rule out (the
    engine constructor validates divisibility up front, so this raise
    is the backstop, not the UX).
    """
    name = path_str.rsplit("/", 1)[-1]
    dim = _POOL_KV_DIM.get(name)
    if dim is None:
        return P()
    if tp <= 1:
        return P()
    if leaf.shape[dim] % tp:
        raise ValueError(
            f"cannot shard {path_str}: kv-heads dim {leaf.shape[dim]} is "
            f"not divisible by {tp_axis}={tp}"
        )
    spec = [None] * leaf.ndim
    spec[dim] = tp_axis
    return P(*spec)


def cache_sharding(cache: Any, mesh: Mesh, tp_axis: str = "tp") -> Any:
    """NamedSharding tree for the engine's paged decode cache (works on
    concrete arrays or ShapeDtypeStructs — anything with shape/ndim)."""
    tp = mesh.shape[tp_axis]

    def rule(path, leaf):
        return NamedSharding(
            mesh, cache_leaf_spec(_path_str(path), leaf, tp, tp_axis)
        )

    return jax.tree_util.tree_map_with_path(rule, cache)


def _leaf_paths(tree: Any):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        yield _path_str(path), leaf


def assert_explicit_sharding(
    tree: Any,
    mesh: Mesh,
    *,
    tp_axis: str = "tp",
    must_shard: Callable[[str], bool] | None = None,
    label: str = "engine state",
) -> int:
    """Coverage lint: every array leaf of ``tree`` must be explicitly
    placed over ``mesh`` — and leaves ``must_shard`` selects (by path)
    must actually be PARTITIONED, not replicated, when the tp axis has
    more than one device.  Raises AssertionError naming the offending
    path; returns the number of leaves checked.

    The check is functional, not type-based (a jit output's sharding
    object may not literally be the NamedSharding the input carried):
    placement = the leaf's device set equals the mesh's; partitioning =
    the per-device shard shape is strictly smaller than the global shape.
    """
    if must_shard is None:
        must_shard = lambda path: "pool_" in path  # noqa: E731
    mesh_devices = set(mesh.devices.flat)
    tp = dict(mesh.shape).get(tp_axis, 1)
    checked = 0
    for path, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array):
            continue
        checked += 1
        sharding = leaf.sharding
        if set(sharding.device_set) != mesh_devices:
            raise AssertionError(
                f"{label}: leaf {path!r} is not placed on the engine mesh "
                f"(devices {sorted(str(d) for d in sharding.device_set)} "
                f"vs mesh {sorted(str(d) for d in mesh_devices)}) — every "
                "state-dict leaf must carry an explicit spec"
            )
        if tp > 1 and leaf.size and must_shard(path):
            if sharding.shard_shape(leaf.shape) == tuple(leaf.shape):
                raise AssertionError(
                    f"{label}: leaf {path!r} ({leaf.shape}, "
                    f"{leaf.nbytes} bytes) is silently REPLICATED across "
                    f"{tp_axis}={tp} — KV pools must shard their kv-heads "
                    "axis (parallel/serving.cache_sharding)"
                )
    return checked
