"""1F1B (one-forward-one-backward) pipeline schedule over a ``pp`` mesh axis.

GPipe (pipeline.py) runs ALL forwards then lets autodiff run all backwards,
so every stage holds residuals for every in-flight microbatch — activation
memory grows O(n_micro).  1F1B interleaves: a stage starts the backward of
microbatch m as soon as the cotangent arrives, so at most ``2*n_stages - 1``
residuals are ever live per stage — activation memory is O(n_stages),
INDEPENDENT of the microbatch count, which is what lets long accumulation
horizons (big global batches) fit in HBM.

TPU-first shape, same as the GPipe member: the whole schedule is ONE
``lax.scan`` inside ``shard_map`` — each tick every device does one forward
unit and one backward unit (garbage-in/garbage-out outside its active
window, with stores masked), activations ``ppermute`` rightward and
cotangents leftward over neighbor ICI links every tick, and the trip count
``n_micro + 2*n_stages - 1`` is static.  The backward recomputes the
stage forward from the saved INPUT activation (per-stage rematerialization:
the residual ring buffer stores inputs, not flax intermediates), which is
the standard memory/FLOPs trade for hand-scheduled pipelines.

Schedule (stage s of L, microbatch m):
  forward  at tick  t = m + s
  backward at tick  t = m + 2L - 1 - s
so the last stage turns a microbatch around in one tick, and stage 0's
steady state alternates strictly F,B — the 1F1B invariant.

No reference analogue (SURVEY.md §2.4: the reference ships no parallelism
code); this completes the pipeline family next to GPipe.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .pipeline import mse_loss
from .ring import shard_map_unchecked


def residual_buffer_depth(n_micro: int, n_stages: int) -> int:
    """Live input-residuals per stage under 1F1B: a residual written at
    tick ``m+s`` is read at ``m+2L-1-s``, so at most ``2L-1`` slots are
    ever occupied — independent of the microbatch count (the schedule's
    memory guarantee, pinned by tests)."""
    return min(n_micro, 2 * n_stages - 1)


def _identity_head(head_params, y):
    del head_params
    return y


def pipeline_1f1b_train(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    microbatches: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    loss: Callable[[jax.Array, jax.Array], jax.Array] = mse_loss,
    head_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    head_params: Any = None,
    collect_input_grads: bool = True,
) -> tuple[jax.Array, Any, Any, jax.Array | None]:
    """Pipelined loss + every gradient a full model needs, 1F1B schedule.

    Args:
      stage_fn: ``(one_stage_params, x) -> y`` with ``y.shape == x.shape``.
      stacked_params: pytree with leading dim ``n_stages``
        (:func:`..pipeline.stack_stage_params`), sharded over ``axis``.
      microbatches: ``[n_micro, ...]`` activation stream (replicated) — the
        OUTPUT of whatever (embedder) runs before the pipelined region.
      targets: ``[n_micro, ...]`` per-microbatch targets (replicated).
      loss: differentiable ``(pred, target) -> scalar``; the total
        objective is the MEAN over microbatches.
      head_fn/head_params: optional differentiable head applied on the
        last stage's output INSIDE the per-microbatch objective (e.g. the
        LM head) — replicated params, shape-changing allowed.  Default:
        identity (targets shaped like the stage output).

    Returns ``(loss, stage_grads, head_grads, d_microbatches)``:
      - loss: scalar mean loss, replicated;
      - stage_grads: shaped/sharded exactly like ``stacked_params``;
      - head_grads: like ``head_params`` (zeros-tree when no head);
      - d_microbatches: ``dLoss/d microbatches`` — feed it to the
        embedder's vjp so gradients flow into everything upstream of the
        pipelined region.  ``collect_input_grads=False`` (the head-less
        wrapper) drops the O(n_micro) collection buffer, its per-tick
        update, and the stream-sized psum entirely and returns None.

    SPMD cost note: the per-microbatch objective (head forward + backward)
    is gated with `lax.cond` so only the LAST stage executes it — inner
    stages run a zeros stub — but warmup/drain ticks on the last stage
    still compute-and-mask it; with a vocab-sized head that waste is
    (2L-1)/(n_micro+2L-1) of head FLOPs, amortized away by n_micro.
    """
    if head_fn is None:
        head_fn = _identity_head
    if head_params is None:
        head_params = {}
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(
            f"stacked_params lead dim {lead} != mesh axis {axis}={n_stages}"
        )
    buf_depth = residual_buffer_depth(n_micro, n_stages)
    ticks = n_micro + 2 * n_stages - 1

    def body(params_local, hparams, stream, tgts):
        params_me = jax.tree.map(lambda leaf: leaf[0], params_local)
        stage = jax.lax.axis_index(axis)
        is_last = stage == n_stages - 1
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        x_shape = stream.shape[1:]
        zeros_x = jnp.zeros(x_shape, stream.dtype)
        init = (
            zeros_x,  # activation arriving from the left
            zeros_x,  # cotangent arriving from the right
            jnp.zeros((buf_depth,) + x_shape, stream.dtype),  # input residuals
            jax.tree.map(lambda p: jnp.zeros_like(p), params_me),  # stage grads
            jax.tree.map(lambda p: jnp.zeros_like(p), hparams),  # head grads
            # stage-0 dx stream (only when the caller wants input grads —
            # it is the one O(n_micro) buffer in the schedule)
            jnp.zeros((n_micro,) + x_shape, stream.dtype)
            if collect_input_grads
            else None,
            jnp.zeros((), jnp.float32),  # loss acc (last stage only)
        )

        def tick(carry, t):
            act_in, ct_in, buf, gacc, hacc, dstream, lacc = carry

            # ---- backward residual read FIRST ---------------------------
            # At tick t = m + 2L-1 (stage 0, full buffer) the forward unit
            # writes microbatch t's input into the very ring slot holding
            # microbatch m's residual; the read and the write never concern
            # the same microbatch in one tick (2L-1-s == s has no integer
            # solution), so reading before writing is always correct and
            # makes buf_depth = 2L-1 sufficient.
            mb = t - (2 * n_stages - 1 - stage)
            active_b = jnp.logical_and(mb >= 0, mb < n_micro)
            mb_c = jnp.clip(mb, 0, n_micro - 1)
            slot = mb_c % buf_depth
            x_saved = jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)

            # ---- forward unit: microbatch mf = t - stage ----------------
            mf = t - stage
            active_f = jnp.logical_and(mf >= 0, mf < n_micro)
            feed = jax.lax.dynamic_index_in_dim(
                stream, jnp.clip(mf, 0, n_micro - 1), 0, keepdims=False
            )
            x = jnp.where(stage == 0, feed, act_in)
            buf = jax.lax.cond(
                active_f,
                lambda b: jax.lax.dynamic_update_index_in_dim(
                    b, x, jnp.clip(mf, 0, n_micro - 1) % buf_depth, 0
                ),
                lambda b: b,
                buf,
            )
            y = stage_fn(params_me, x)

            # ---- backward unit: microbatch mb = t - (2L - 1 - stage) ----
            tgt = jax.lax.dynamic_index_in_dim(tgts, mb_c, 0, keepdims=False)
            # Recompute this stage's forward from the saved input and pull
            # gradients through it (per-stage remat).
            y2, vjp = jax.vjp(stage_fn, params_me, x_saved)
            # Cotangent seed: the last stage differentiates the full
            # per-microbatch objective loss(head(y), tgt) — head params
            # included; inner stages use the ppermuted cotangent.  The
            # objective (head fwd+bwd — vocab-sized for an LM) is gated
            # with lax.cond so inner stages run a zeros stub instead of
            # computing-and-discarding it every tick; the predicate is
            # per-device-constant, so each device compiles to one path.
            y2f = y2.astype(jnp.float32)

            def run_objective(args):
                hp, yy = args
                return jax.value_and_grad(
                    lambda hp, yy: loss(head_fn(hp, yy), tgt), argnums=(0, 1)
                )(hp, yy)

            def stub_objective(args):
                hp, yy = args
                return jnp.zeros((), yy.dtype), (
                    jax.tree.map(jnp.zeros_like, hp),
                    jnp.zeros_like(yy),
                )

            lval, (dhp, dy) = jax.lax.cond(
                is_last, run_objective, stub_objective, (hparams, y2f)
            )
            ct_use = jnp.where(is_last, (dy / n_micro).astype(y2.dtype), ct_in)
            dparams, dx = vjp(ct_use)
            gmask = active_b.astype(jnp.float32)
            gacc = jax.tree.map(
                lambda g, d: g + gmask.astype(d.dtype) * d, gacc, dparams
            )
            hmask = jnp.logical_and(active_b, is_last).astype(jnp.float32)
            hacc = jax.tree.map(
                lambda g, d: g + (hmask / n_micro).astype(d.dtype) * d, hacc, dhp
            )
            if dstream is not None:
                # Stage 0's dx is dLoss/d(stream microbatch mb) — collect.
                write_dstream = jnp.logical_and(active_b, stage == 0)
                dstream = jax.lax.cond(
                    write_dstream,
                    lambda ds: jax.lax.dynamic_update_index_in_dim(
                        ds, dx.astype(ds.dtype), mb_c, 0
                    ),
                    lambda ds: ds,
                    dstream,
                )
            lacc = lacc + jnp.where(
                jnp.logical_and(active_b, is_last), lval.astype(jnp.float32), 0.0
            )

            # ---- neighbor exchange (collectives run unconditionally) ----
            act_next = jax.lax.ppermute(y, axis, fwd_perm)
            ct_next = jax.lax.ppermute(dx, axis, bwd_perm)
            return (act_next, ct_next, buf, gacc, hacc, dstream, lacc), None

        (_, _, _, gacc, hacc, dstream, lacc), _ = jax.lax.scan(
            tick, init, jnp.arange(ticks)
        )
        # Loss/head-grads live on the last stage, dstream on stage 0; the
        # other devices contributed zeros, so psum replicates all three.
        loss_total = jax.lax.psum(lacc, axis) / n_micro
        grads_out = jax.tree.map(lambda g: g[None], gacc)
        head_grads = jax.tree.map(lambda g: jax.lax.psum(g, axis), hacc)
        dstream_out = (
            jax.lax.psum(dstream, axis) if dstream is not None else None
        )
        return loss_total, grads_out, head_grads, dstream_out

    in_specs = (
        jax.tree.map(lambda _: P(axis), stacked_params),
        jax.tree.map(lambda _: P(), head_params),
        P(),
        P(),
    )
    out_specs = (
        P(),
        jax.tree.map(lambda _: P(axis), stacked_params),
        jax.tree.map(lambda _: P(), head_params),
        P() if collect_input_grads else None,
    )
    fn = shard_map_unchecked(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs
    )
    return fn(stacked_params, head_params, microbatches, targets)


def pipeline_1f1b_grads(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    microbatches: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    loss: Callable[[jax.Array, jax.Array], jax.Array] = mse_loss,
) -> tuple[jax.Array, Any]:
    """Head-less convenience wrapper: ``(loss, stage_grads)`` — see
    :func:`pipeline_1f1b_train` for the full-model version.  Skips the
    O(n_micro) input-grad collection buffer it would never read."""
    loss_total, grads, _, _ = pipeline_1f1b_train(
        stage_fn, stacked_params, microbatches, targets, mesh, axis, loss,
        collect_input_grads=False,
    )
    return loss_total, grads
