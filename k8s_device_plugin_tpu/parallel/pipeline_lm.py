"""The decoder LM run as a GPipe pipeline: pp applied to a real model.

pipeline.py supplies the spatial schedule for any chainable stage; this
module instantiates it for models/transformer.py's decoder: the embedding
and LM head live OUTSIDE the pipelined region (replicated — they are cheap
and shape-changing), while the ``num_layers`` decoder blocks are divided
into ``pp`` equal stages whose parameters live permanently on their stage's
devices.  Microbatches stream through the stages; autodiff of the schedule
gives the GPipe backward pass.

Composition note: the stage axis should be the OUTER (slowest, possibly
DCN-crossing) mesh axis — stage hops are point-to-point and
latency-tolerant, unlike tp/sp collectives (docs/parallelism.md).
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from ..models.train import TrainState, softmax_xent
from ..models.transformer import DecoderBlock, GPTConfig, RMSNorm
from .pipeline import pipeline_apply, stack_stage_params


class DecoderStage(nn.Module):
    """``layers`` consecutive decoder blocks — one pipeline stage."""

    config: GPTConfig
    layers: int

    @nn.compact
    def __call__(self, hidden, positions):
        block_cls = (
            nn.remat(DecoderBlock, static_argnums=())
            if self.config.remat
            else DecoderBlock
        )
        for i in range(self.layers):
            hidden = block_cls(self.config, name=f"block_{i}")(hidden, positions)
        return hidden


class _Embedder(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, ids):
        return nn.Embed(
            self.config.vocab_size,
            self.config.hidden_size,
            dtype=self.config.dtype,
            name="embed",
        )(ids)


class _Head(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, hidden):
        hidden = RMSNorm(dtype=self.config.dtype, name="final_norm")(hidden)
        return nn.Dense(
            self.config.vocab_size, dtype=jnp.float32, use_bias=False, name="lm_head"
        )(hidden)


class PipelinedLM:
    """Decoder LM with its blocks sharded over a ``pp`` mesh axis.

    Usage:
        plm = PipelinedLM(cfg, mesh, n_micro=8)
        params = plm.init(rng, sample_ids)
        state = plm.create_train_state(params, tx)
        step = jax.jit(plm.make_train_step(tx), donate_argnums=0)
    """

    def __init__(self, config: GPTConfig, mesh: Mesh, n_micro: int, axis: str = "pp"):
        self.config = config
        self.mesh = mesh
        self.axis = axis
        self.n_micro = n_micro
        self.n_stages = mesh.shape[axis]
        if config.num_layers % self.n_stages:
            raise ValueError(
                f"num_layers {config.num_layers} not divisible by "
                f"{axis}={self.n_stages} stages"
            )
        self.layers_per_stage = config.num_layers // self.n_stages
        self._embed = _Embedder(config)
        self._stage = DecoderStage(config, self.layers_per_stage)
        self._head = _Head(config)

    @staticmethod
    def _positions(batch: int, seq: int) -> jax.Array:
        """[batch, seq] position ids — the one definition every path uses."""
        return jnp.broadcast_to(jnp.arange(seq)[None, :], (batch, seq))

    # ---------------------------------------------------------- parameters
    def init(self, rng: jax.Array, sample_ids: jax.Array) -> dict:
        """Parameter pytree: {embed, stages (leading dim = n_stages), head}."""
        b, s = sample_ids.shape
        positions = self._positions(b, s)
        k_embed, k_head, *k_stages = jax.random.split(rng, 2 + self.n_stages)
        embed = self._embed.init(k_embed, sample_ids)["params"]
        hidden = self._embed.apply({"params": embed}, sample_ids)
        stages = [
            self._stage.init(k, hidden, positions)["params"] for k in k_stages
        ]
        head = self._head.init(k_head, hidden)["params"]
        return {
            "embed": embed,
            "stages": stack_stage_params(stages),
            "head": head,
        }

    # ------------------------------------------------------------- forward
    def _microbatch(self, ids: jax.Array) -> jax.Array:
        b = ids.shape[0]
        if b % self.n_micro:
            raise ValueError(f"batch {b} not divisible by n_micro={self.n_micro}")
        return ids.reshape(self.n_micro, b // self.n_micro, *ids.shape[1:])

    def apply(self, params: dict, ids: jax.Array) -> jax.Array:
        """[batch, seq] ids -> [batch, seq, vocab] float32 logits."""
        b, s = ids.shape
        micro_ids = self._microbatch(ids)
        hidden = self._embed.apply({"params": params["embed"]}, micro_ids)
        positions = self._positions(b // self.n_micro, s)

        def stage_fn(stage_params, x):
            return self._stage.apply({"params": stage_params}, x, positions)

        out = pipeline_apply(
            stage_fn, params["stages"], hidden, self.mesh, self.axis
        )
        logits = self._head.apply({"params": params["head"]}, out)
        return logits.reshape(b, s, -1)

    def apply_serial(self, params: dict, ids: jax.Array) -> jax.Array:
        """Pipeline-free reference forward (same params): the numerics
        oracle for tests — stages applied in order on the full batch."""
        b, s = ids.shape
        hidden = self._embed.apply({"params": params["embed"]}, ids)
        positions = self._positions(b, s)
        for i in range(self.n_stages):
            stage_i = jax.tree.map(lambda leaf: leaf[i], params["stages"])
            hidden = self._stage.apply({"params": stage_i}, hidden, positions)
        return self._head.apply({"params": params["head"]}, hidden)

    # ------------------------------------------------------------ training
    def create_train_state(self, params: dict, tx) -> TrainState:
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats={},
        )

    def make_train_step(
        self,
        tx,
        loss_fn: Callable[[jax.Array, jax.Array], jax.Array] = softmax_xent,
        schedule: str = "gpipe",
    ):
        """``schedule``: "gpipe" (autodiff backward after all forwards —
        simple, O(n_micro) activation memory) or "1f1b" (hand-interleaved
        schedule, O(n_stages) activation memory — see pipeline_1f1b.py).
        Both optimize the identical objective; grads for embed/head flow
        through the 1F1B kernel's d_microbatches/head-grad outputs."""
        if schedule == "gpipe":
            def train_step(state: TrainState, batch: dict):
                def compute_loss(params):
                    logits = self.apply(params, batch["input_ids"])
                    return loss_fn(logits, batch["labels"])

                loss, grads = jax.value_and_grad(compute_loss)(state.params)
                return self._apply_updates(tx, state, grads, loss)

            return train_step
        if schedule != "1f1b":
            raise ValueError(f"schedule must be gpipe|1f1b, got {schedule!r}")

        from .pipeline_1f1b import pipeline_1f1b_train

        def train_step_1f1b(state: TrainState, batch: dict):
            params = state.params
            micro_ids = self._microbatch(batch["input_ids"])
            micro_labels = self._microbatch(batch["labels"])
            s = batch["input_ids"].shape[1]
            positions = self._positions(micro_ids.shape[1], s)
            hidden, embed_vjp = jax.vjp(
                lambda ep: self._embed.apply({"params": ep}, micro_ids),
                params["embed"],
            )

            def stage_fn(stage_params, x):
                return self._stage.apply({"params": stage_params}, x, positions)

            def head_fn(head_params, y):
                return self._head.apply({"params": head_params}, y)

            loss, g_stages, g_head, d_hidden = pipeline_1f1b_train(
                stage_fn,
                params["stages"],
                hidden,
                micro_labels,
                self.mesh,
                self.axis,
                loss=loss_fn,
                head_fn=head_fn,
                head_params=params["head"],
            )
            (g_embed,) = embed_vjp(d_hidden)
            grads = {"embed": g_embed, "stages": g_stages, "head": g_head}
            return self._apply_updates(tx, state, grads, loss)

        return train_step_1f1b

    @staticmethod
    def _apply_updates(tx, state: TrainState, grads, loss):
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        return (
            state.with_updates(
                step=state.step + 1,
                params=optax.apply_updates(state.params, updates),
                opt_state=new_opt,
            ),
            loss,
        )
