"""Sharding rules: GSPMD-style annotate-and-let-XLA-partition.

No hand-written collectives here — we lay out batch and parameters over the
mesh with NamedSharding and let XLA insert the all-reduces/all-gathers
(scaling-book recipe: pick a mesh, annotate, compile).  Two axes are used by
the benchmark workloads:

- ``dp``: batch (data-parallel) axis — gradients all-reduce over ICI.
- ``mp``: parameter axis — large weights are sharded FSDP-style; XLA
  all-gathers them per layer and reduce-scatters the grads.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def batch_tree_sharding(batch: Any, mesh: Mesh, axis: str = "dp") -> Any:
    return jax.tree.map(lambda _: batch_sharding(mesh, axis), batch)


def param_sharding(
    params: Any,
    mesh: Mesh,
    axis: str = "mp",
    min_weight_size: int = 2**14,
) -> Any:
    """Per-leaf rule: shard the largest dimension divisible by the mesh axis
    size, for leaves big enough to be worth it; replicate the rest.

    This is the standard FSDP-ish layout for models whose layers are dense
    blocks: XLA turns the annotations into all-gather-on-use /
    reduce-scatter-on-grad over the ``mp`` axis.
    """
    axis_size = mesh.shape[axis]

    def rule(leaf) -> NamedSharding:
        if not hasattr(leaf, "shape") or leaf.size < min_weight_size:
            return replicated(mesh)
        dims = np.argsort(leaf.shape)[::-1]  # largest dim first
        for d in dims:
            if leaf.shape[d] % axis_size == 0:
                spec = [None] * leaf.ndim
                spec[int(d)] = axis
                return NamedSharding(mesh, P(*spec))
        return replicated(mesh)

    return jax.tree.map(rule, params)


def state_sharding(state: Any, mesh: Mesh, axis: str = "mp", **kwargs) -> Any:
    """Sharding tree for a models.train.TrainState: params and optimizer
    moments follow the param rule (they are param-shaped); step is replicated."""
    params_sh = param_sharding(state.params, mesh, axis, **kwargs)

    def like_params(tree):
        # Optimizer state contains param-shaped pytrees (adam moments) plus
        # scalars (counts); map shapes through the same rule.
        return param_sharding(tree, mesh, axis, **kwargs)

    return type(state)(
        step=replicated(mesh),
        params=params_sh,
        opt_state=like_params(state.opt_state),
        batch_stats=like_params(state.batch_stats),
    )


def shard_train_step(
    train_step,
    mesh: Mesh,
    state: Any,
    batch: Any,
    axis_mp: str = "mp",
    batch_axis: str = "dp",
    state_sharding_fn=None,
    batch_sharding_fn=None,
):
    """jit the train step with explicit in/out shardings and donated state.

    Returns ``(jitted_step, sharded_state, batch_shardings)``; the caller
    device_puts batches with ``batch_shardings`` (or relies on jit's implicit
    transfer) and loops.  ``state_sharding_fn`` overrides the default
    FSDP-over-``axis_mp`` state layout (tensor.py passes its tp rules);
    ``batch_sharding_fn`` overrides the batch-over-``batch_axis`` input
    layout (sequence.py passes dp×sp).
    """
    if state_sharding_fn is None:
        state_sh = state_sharding(state, mesh, axis_mp)
    else:
        state_sh = state_sharding_fn(state)
    if batch_sharding_fn is None:
        batch_sh = batch_tree_sharding(batch, mesh, batch_axis)
    else:
        batch_sh = batch_sharding_fn(batch)
    placed_state = jax.device_put(state, state_sh)
    step = jax.jit(
        train_step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=0,
    )
    return step, placed_state, batch_sh
