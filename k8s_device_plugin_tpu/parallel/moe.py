"""Mixture-of-experts with expert parallelism, GShard/Switch style.

The reference has no model or parallelism code (SURVEY.md §2.4); this is the
expert-parallel member of the workload family the TPU plugin allocates chips
to.  TPU-first design: routing is dense one-hot dispatch/combine einsums with
fully static shapes — no gather/scatter, no data-dependent control flow — so
XLA tiles everything onto the MXU; the expert dimension of the kernels is
annotated over an ``ep`` mesh axis (parallel/tensor.py's ``experts_*`` rules)
and GSPMD lowers the dispatch einsums to all-to-alls over ICI, exactly the
GShard recipe.

Capacity model: each expert processes at most ``capacity_factor *
tokens_per_group / num_experts`` tokens per group (group = one sequence);
over-capacity tokens fall through the residual connection (their combine
weight is zero), keeping shapes static at the cost of dropped-token error —
standard for Switch/GShard training.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..models.transformer import GPTConfig


class MoeMlp(nn.Module):
    """Drop-in replacement for models.transformer.SwiGluMlp.

    Parameters (shapes chosen for parallel/tensor.py's sharding rules):
      router/kernel        [embed, experts]            (replicated)
      experts_gate/kernel  [experts, embed, ffn]       (ep, -, tp)
      experts_up/kernel    [experts, embed, ffn]       (ep, -, tp)
      experts_down/kernel  [experts, ffn, embed]       (ep, tp, -)
    """

    config: GPTConfig
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        if x.ndim == 2:  # tolerate [tokens, embed] by adding a group dim
            x = x[None]
            squeeze = True
        else:
            squeeze = False
        g, s, d = x.shape
        e = self.num_experts
        capacity = max(1, math.ceil(self.capacity_factor * s / e))

        # --- routing (float32 for a stable softmax) ---------------------
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(x.astype(jnp.float32))  # [g, s, e]
        probs = jax.nn.softmax(router_logits, axis=-1)

        # Top-k one-hot assignment, k selections in sequence (k is tiny and
        # static, so this unrolled Python loop is compiler-friendly).
        combine = jnp.zeros((g, s, e, capacity), jnp.float32)
        remaining = probs
        # Running per-expert fill count, advanced after each selection round.
        fill = jnp.zeros((g, e), jnp.int32)
        for _ in range(self.experts_per_token):
            choice = jnp.argmax(remaining, axis=-1)  # [g, s]
            onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [g, s, e]
            # Position of each token within its chosen expert's buffer this
            # round: tokens earlier in the sequence fill earlier slots.
            pos_in_round = (jnp.cumsum(onehot, axis=1) - onehot)  # [g, s, e]
            pos = pos_in_round + fill[:, None, :]
            pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [g, s]
            keep = (pos_tok < capacity).astype(jnp.float32)  # [g, s]
            weight = jnp.sum(remaining * onehot, axis=-1) * keep  # [g, s]
            slot = jax.nn.one_hot(
                jnp.minimum(pos_tok, capacity - 1), capacity, dtype=jnp.float32
            )  # [g, s, c]
            combine = combine + (
                weight[..., None, None] * onehot[..., :, None] * slot[..., None, :]
            )
            fill = fill + jnp.sum(onehot * keep[..., None], axis=1).astype(jnp.int32)
            remaining = remaining * (1.0 - onehot)

        # Normalize the kept gates so the combine weights of each token sum
        # to 1 (unless everything it picked was over capacity).
        total = jnp.sum(combine, axis=(-2, -1), keepdims=True)
        combine = jnp.where(total > 0, combine / jnp.maximum(total, 1e-9), 0.0)
        dispatch = (combine > 0).astype(x.dtype)  # [g, s, e, c]

        # Load-balance auxiliary loss (Switch form, N·Σ f·P): mean fraction
        # of tokens per expert * mean router prob per expert, scaled by e.
        # sow() is a no-op unless the caller makes 'intermediates' mutable —
        # models.train.make_train_step(aux_loss_coeff=...) does that and adds
        # this to the loss; plain apply() silently drops it.
        frac_tokens = jnp.mean(dispatch.sum(axis=-1), axis=1)  # [g, e]
        frac_probs = jnp.mean(probs, axis=1)  # [g, e]
        aux = jnp.mean(jnp.sum(frac_tokens * frac_probs, axis=-1)) * e
        self.sow("intermediates", "moe_aux_loss", aux)

        # --- dispatch -> expert SwiGLU -> combine ------------------------
        ffn = cfg.intermediate_size
        init = nn.initializers.lecun_normal()
        w_gate = self.param("experts_gate", lambda r: init(r, (e, d, ffn))).astype(cfg.dtype)
        w_up = self.param("experts_up", lambda r: init(r, (e, d, ffn))).astype(cfg.dtype)
        w_down = self.param("experts_down", lambda r: init(r, (e, ffn, d))).astype(cfg.dtype)

        # expert_in: [e, g, c, d] — GSPMD turns this einsum into the
        # all-to-all that ships token slots to their expert's ep shard.
        expert_in = jnp.einsum("gsec,gsd->egcd", dispatch, x)
        gate = jnp.einsum("egcd,edf->egcf", expert_in, w_gate)
        up = jnp.einsum("egcd,edf->egcf", expert_in, w_up)
        act = nn.silu(gate) * up
        expert_out = jnp.einsum("egcf,efd->egcd", act, w_down)
        out = jnp.einsum(
            "gsec,egcd->gsd", combine.astype(expert_out.dtype), expert_out
        )
        out = out.astype(cfg.dtype)
        return out[0] if squeeze else out


def moe_mlp_factory(
    config: GPTConfig,
    num_experts: int = 8,
    experts_per_token: int = 2,
    capacity_factor: float = 1.25,
):
    """mlp_factory for models.transformer.DecoderBlock / TransformerLM:
    ``TransformerLM(cfg, mlp_factory=moe_mlp_factory(cfg, 8))`` builds a
    fully MoE decoder."""

    def factory():
        return MoeMlp(
            config,
            num_experts=num_experts,
            experts_per_token=experts_per_token,
            capacity_factor=capacity_factor,
            name="moe",
        )

    return factory
