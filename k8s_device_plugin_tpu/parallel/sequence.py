"""Long-context training: sequence parallelism wired into the decoder LM.

Ties the two sp attention engines (ring.py: neighbor-hop kv rotation;
ulysses.py: all-to-all head/sequence exchange) into
models/transformer.TransformerLM through its ``attention_fn`` hook, and
builds train steps whose BATCH is sharded over ``dp`` and SEQUENCE over
``sp`` — the layout that makes million-token contexts fit: every
positionwise op (embeddings, norms, MLPs, losses) runs on its local
sequence shard under GSPMD, and only attention communicates, through the
explicit shard_map engines riding ICI.

The reference has nothing remotely comparable (SURVEY.md §5.7: "long-context
/ sequence parallelism — absent, nothing to scale"); this module exists
because the TPU build treats long context as first-class.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring import ring_self_attention
from .sharding import shard_train_step
from .tensor import tp_state_sharding
from .ulysses import ulysses_self_attention

_ENGINES = {
    "ulysses": ulysses_self_attention,
    "ring": ring_self_attention,
}


def sp_attention_fn(
    mesh: Mesh,
    axis: str = "sp",
    kind: str = "ulysses",
    dp_axis: str = "dp",
    tp_axis: str = "tp",
):
    """Attention override for TransformerLM(attention_fn=...): exact causal
    attention over a sequence sharded on ``axis``.

    kind="ulysses": one all-to-all per tensor, needs local heads % sp == 0 —
    wins when heads are plentiful and the exchange fits ICI bisection
    bandwidth.  kind="ring": kv shards rotate around the ring, any head
    count — wins for very long sequences or head-poor models.  Both are
    exact, so checkpoints and losses are interchangeable with the dense path.

    When the mesh also has ``dp_axis``/``tp_axis``, the batch/head dims stay
    sharded over them through the engine (no all-gather at the shard_map
    boundary) — attention compute and memory per device really is
    batch/dp × heads/tp × seq/sp.
    """
    try:
        engine = _ENGINES[kind]
    except KeyError:
        raise ValueError(f"unknown sp attention kind {kind!r}; use {sorted(_ENGINES)}")

    bound = functools.partial(
        engine,
        mesh=mesh,
        axis=axis,
        batch_axis=dp_axis if dp_axis in mesh.axis_names else None,
        head_axis=tp_axis if tp_axis in mesh.axis_names else None,
    )
    # Both engines consume grouped-query k/v natively now — ring keeps the
    # rotating kv shard un-expanded (ring.py), Ulysses rides kv through its
    # own group-times-smaller all_to_all when kv_heads divides sp (and both
    # expand internally in the configs where sharding forbids it).
    # transformer.CausalSelfAttention reads this to skip its GQA repeat.
    bound.supports_gqa = True
    return bound


def sp_batch_sharding(batch: Any, mesh: Mesh, dp_axis: str = "dp", sp_axis: str = "sp"):
    """[batch, seq] token arrays sharded batch-over-dp, sequence-over-sp."""
    return jax.tree.map(lambda _: NamedSharding(mesh, P(dp_axis, sp_axis)), batch)


def shard_train_step_sp(
    train_step,
    mesh: Mesh,
    state: Any,
    batch: Any,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    tp_axis: str = "tp",
):
    """jit a TransformerLM train step with dp×sp input sharding.

    The model must have been built with ``attention_fn=sp_attention_fn(mesh,
    sp_axis, ...)`` — positionwise compute then follows the input sharding
    under GSPMD while attention communicates through the explicit engine.
    Parameters follow tensor.py's tp rules (replicated when the mesh has no
    ``tp`` axis), so sp composes freely with tensor parallelism.

    Returns ``(jitted_step, placed_state, batch_shardings)``.
    """
    return shard_train_step(
        train_step,
        mesh,
        state,
        batch,
        state_sharding_fn=lambda s: tp_state_sharding(s, mesh, tp_axis),
        batch_sharding_fn=lambda b: sp_batch_sharding(b, mesh, dp_axis, sp_axis),
    )
