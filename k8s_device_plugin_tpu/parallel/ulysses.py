"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second classic long-context layout (alongside ring attention, ring.py):
instead of streaming kv shards around the ring, ONE all-to-all per tensor
re-partitions [batch, heads, seq/n, head_dim] into [batch, heads/n, seq,
head_dim] — every device then holds the FULL sequence for a SUBSET of heads,
runs an ordinary (flash) attention locally with no inner-loop communication,
and a reverse all-to-all restores sequence sharding.  Traffic is O(seq·d)
per device in two bursts that XLA lowers to ICI all-to-alls, versus ring's
n neighbor hops overlapped with compute; Ulysses wins when heads ≥ n and the
all-to-all fits comfortably in ICI bisection bandwidth, ring wins for very
long sequences or few heads.  (Pattern from the DeepSpeed-Ulysses paper;
built here on jax.lax.all_to_all inside shard_map — the reference has no
distributed compute at all, SURVEY.md §2.4.)

Layering mirrors ring.py: `ulysses_attention` is the per-device body (call
inside shard_map with the axis bound); `ulysses_self_attention` wraps a
global array view over a Mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.flash_attention import flash_attention, mha_reference
from .ring import expand_gqa_kv, shard_map_unchecked


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Per-device Ulysses body.

    Local shard shapes [batch, heads, local_seq, head_dim]; global seq =
    local_seq * n where n = size of ``axis_name``; heads must divide by n.
    Must run inside shard_map (or pmap) with ``axis_name`` bound.

    Grouped-query attention: when ``kv_heads %% n == 0`` the kv tensors ride
    their own (group-times smaller) all-to-all and the local attention runs
    GQA-natively through the flash kernel; otherwise kv is expanded to full
    heads first (the pre-GQA behavior).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)  # concrete under shard_map
    if q.shape[1] % n:
        raise ValueError(
            f"heads {q.shape[1]} not divisible by {axis_name}={n}; "
            "use ring attention for head-poor long-context models"
        )
    kv_heads = k.shape[1]
    if q.shape[1] % kv_heads:
        raise ValueError(
            f"q heads {q.shape[1]} not a multiple of kv heads {kv_heads}"
        )
    if kv_heads != q.shape[1] and kv_heads % n:
        # Too few kv heads to scatter over the axis: expand to full heads
        # (the attention itself would handle GQA; the all-to-all cannot).
        k, v = expand_gqa_kv(q, k, v)

    def scatter_heads(x):
        # [b, h, s/n, d] -> [b, h/n, s, d]: each device trades head blocks
        # for sequence blocks with every ring peer in one all-to-all.
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def gather_heads(x):
        # [b, h/n, s, d] -> [b, h, s/n, d]: the inverse exchange.
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    q_full = scatter_heads(q)
    k_full = scatter_heads(k)
    v_full = scatter_heads(v)
    # Full-sequence attention on the owned heads.  128-tileable sequences go
    # through the O(seq)-memory flash kernel (ops/flash_attention.py) — no
    # [seq, seq] score matrix is ever materialized; anything else falls back
    # to the plain-XLA oracle (same policy as models/transformer.py) instead
    # of failing deep inside Pallas block validation.
    seq_full = q_full.shape[2]
    block = min(128, seq_full)
    if seq_full % block == 0:
        out_full = flash_attention(
            q_full, k_full, v_full, causal=causal, sm_scale=sm_scale
        )
    else:
        out_full = mha_reference(
            q_full, k_full, v_full, causal=causal, sm_scale=sm_scale
        )
    return gather_heads(out_full)


def ulysses_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    sm_scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
) -> jax.Array:
    """Global-view wrapper: [batch, heads, seq, head_dim] arrays, sequence
    sharded over ``mesh`` axis ``axis``; returns the same global shape.
    Requires local heads % mesh.shape[axis] == 0 (the head-scatter step).

    ``batch_axis``/``head_axis`` name mesh axes the batch/head dims are
    already sharded over (dp / tp in a composed mesh) so those dims stay
    sharded through the exchange instead of being all-gathered at the
    shard_map boundary; with ``head_axis`` set, the heads each device
    scatters are its local (tp-sharded) head group.
    """
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"seq {q.shape[2]} not divisible by {axis}={n}")
    local_heads = q.shape[1] // (mesh.shape[head_axis] if head_axis else 1)
    if local_heads % n:
        raise ValueError(
            f"local heads {local_heads} not divisible by {axis}={n}; "
            "use ring attention for head-poor long-context models"
        )
    if head_axis and k.shape[1] != q.shape[1] and k.shape[1] % mesh.shape[head_axis]:
        # GQA kv heads can't shard over the tp axis: expand before placing
        # (same fallback as ring_self_attention) instead of an opaque
        # device_put failure.
        k, v = expand_gqa_kv(q, k, v)
    spec = P(batch_axis, head_axis, axis, None)
    body = functools.partial(
        ulysses_attention, axis_name=axis, causal=causal, sm_scale=sm_scale
    )
    # The Pallas call inside the body reports no varying-manual-axes info on
    # its outputs, so shard_map's vma checking must be off (check_rep on
    # pre-0.8 jax spellings).
    shard_mapped = shard_map_unchecked(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    sharding = NamedSharding(mesh, spec)
    return shard_mapped(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
