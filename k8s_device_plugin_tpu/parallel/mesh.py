"""Device-mesh construction for workloads running inside allocated pods.

The plugin injects TPU_VISIBLE_CHIPS / TPU_CHIPS_PER_HOST_BOUNDS /
TPU_WORKER_* (plugin/envs.py); libtpu consumes those to enumerate chips.  This
module is the workload-side counterpart: turn `jax.devices()` plus the
injected env into a `jax.sharding.Mesh` whose axes line up with the physical
ICI block the plugin granted, so collectives ride ICI links instead of
arbitrary permutations.
"""

from __future__ import annotations

import math
import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def chips_per_host_bounds(environ: Mapping[str, str] | None = None) -> tuple[int, ...] | None:
    environ = os.environ if environ is None else environ
    text = environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if not text:
        return None
    try:
        return tuple(int(v) for v in text.split(","))
    except ValueError:
        return None


def make_mesh(
    axes: Mapping[str, int] | None = None,
    devices: Sequence | None = None,
) -> Mesh:
    """Build a Mesh.

    ``axes`` maps axis name -> size in declaration order, e.g.
    ``{"dp": 2, "mp": 4}``; sizes must multiply to the device count.  A size of
    -1 means "whatever is left" (at most one).  Default: all devices on one
    data-parallel axis ``{"dp": -1}``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    axes = dict(axes) if axes else {"dp": -1}
    n = len(devices)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(f"axes {axes} do not cover {n} devices")
    grid = np.array(devices).reshape(sizes)
    return Mesh(grid, tuple(axes.keys()))


def make_host_mesh(
    axes: Mapping[str, int] | None = None,
    environ: Mapping[str, str] | None = None,
) -> Mesh:
    """Mesh over this process's addressable devices, ordered so that the
    trailing mesh axis walks the x-direction of the granted ICI block (device
    order from libtpu already follows the injected TPU_VISIBLE_CHIPS order)."""
    return make_mesh(axes, devices=jax.local_devices())
