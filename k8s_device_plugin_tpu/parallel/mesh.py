"""Device-mesh construction for workloads running inside allocated pods.

The plugin injects TPU_VISIBLE_CHIPS / TPU_CHIPS_PER_HOST_BOUNDS /
TPU_WORKER_* (plugin/envs.py); libtpu consumes those to enumerate chips.  This
module is the workload-side counterpart: turn `jax.devices()` plus the
injected env into a `jax.sharding.Mesh` whose axes line up with the physical
ICI block the plugin granted, so collectives ride ICI links instead of
arbitrary permutations.

`mesh_from_allocation` is the serving-side entry: a 1-axis ``tp`` mesh over
EXACTLY the chips the plugin allocated, ordered so consecutive mesh
neighbors are physical ICI neighbors (the all-reduce a tensor-parallel
decode step inserts then rides nearest-neighbor links end to end).
"""

from __future__ import annotations

import math
import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..plugin.topology import chip_index


def chips_per_host_bounds(environ: Mapping[str, str] | None = None) -> tuple[int, ...] | None:
    environ = os.environ if environ is None else environ
    text = environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if not text:
        return None
    try:
        return tuple(int(v) for v in text.split(","))
    except ValueError:
        return None


def allocated_chip_indices(environ: Mapping[str, str] | None = None) -> list[int] | None:
    """The host-local chip indices the plugin granted this container
    (TPU_VISIBLE_CHIPS, plugin/envs.py), or None off-cluster / unparsable.
    Order is the plugin's sorted-index order — the same order libtpu
    enumerates the container's devices in."""
    environ = os.environ if environ is None else environ
    text = environ.get("TPU_VISIBLE_CHIPS")
    if not text:
        return None
    try:
        return [int(v) for v in text.split(",")]
    except ValueError:
        return None


def snake_order(bounds: Sequence[int]) -> list[int]:
    """Local chip indices of the ``bounds`` block in boustrophedon order:
    x sweeps alternate direction per row, y per plane, so every
    consecutive pair differs by one step along exactly one axis — i.e.
    consecutive entries are physical ICI neighbors.  Laying the ``tp``
    mesh axis along this walk keeps the decode all-reduce's ring on
    nearest-neighbor links (the reason GetPreferredAllocation hands out
    contiguous blocks in the first place)."""
    bx, by, bz = (tuple(bounds) + (1, 1, 1))[:3]
    order: list[int] = []
    xdir = ydir = 1
    for z in range(bz):
        ys = range(by) if ydir > 0 else range(by - 1, -1, -1)
        for y in ys:
            xs = range(bx) if xdir > 0 else range(bx - 1, -1, -1)
            for x in xs:
                order.append(chip_index((x, y, z), (bx, by, bz)))
            xdir = -xdir
        ydir = -ydir
    return order


def mesh_from_allocation(
    tp: int,
    *,
    environ: Mapping[str, str] | None = None,
    devices: Sequence | None = None,
    tp_axis: str = "tp",
) -> Mesh:
    """A 1-axis ``tp`` mesh over the chips the plugin actually allocated.

    On-cluster (TPU_VISIBLE_CHIPS injected): the allocation IS the mesh —
    ``tp`` must equal the granted chip count (a clear error names both
    otherwise; a pod asking for tensor parallelism across chips it was not
    granted would otherwise shard over whatever ``jax.devices()`` happens
    to return), and the axis walks the granted block's ICI bounds in
    snake order so neighboring shards sit on neighboring chips.

    Off-cluster (no env): falls back to ``make_mesh`` over the first
    ``tp`` of ``jax.devices()`` — the CPU-dryrun / local-dev path.

    ``devices`` overrides device discovery (tests, dryruns); on-cluster it
    must follow TPU_VISIBLE_CHIPS order like ``jax.local_devices()`` does.
    """
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    environ = os.environ if environ is None else environ
    chips = allocated_chip_indices(environ)
    if chips is None:
        devices = list(jax.devices()) if devices is None else list(devices)
        if tp > len(devices):
            raise ValueError(
                f"--tp {tp} needs {tp} devices but only {len(devices)} are "
                "visible (no TPU_VISIBLE_CHIPS injected: off-cluster "
                "fallback over jax.devices())"
            )
        return make_mesh({tp_axis: tp}, devices=devices[:tp])
    if len(chips) != tp:
        raise ValueError(
            f"--tp {tp} does not match the allocation: the plugin injected "
            f"{len(chips)} chip(s) (TPU_VISIBLE_CHIPS="
            f"{environ.get('TPU_VISIBLE_CHIPS')!r}).  Request a pod with "
            f"exactly {tp} chips or set --tp {len(chips)}."
        )
    devices = list(jax.local_devices()) if devices is None else list(devices)
    if len(devices) < tp:
        raise ValueError(
            f"the allocation grants {tp} chip(s) but only {len(devices)} "
            "JAX device(s) are visible — libtpu did not honor "
            "TPU_VISIBLE_CHIPS, or the process runs on the wrong backend"
        )
    devices = devices[:tp]
    bounds = chips_per_host_bounds(environ)
    if bounds is not None and math.prod(bounds) == tp:
        # Device i is the chip at local block index i (x fastest — the
        # injected-bounds convention, plugin/topology.py); reorder along
        # the snake walk so the tp ring rides adjacent ICI links.
        devices = [devices[i] for i in snake_order(bounds)]
    return Mesh(np.array(devices), (tp_axis,))


def make_mesh(
    axes: Mapping[str, int] | None = None,
    devices: Sequence | None = None,
) -> Mesh:
    """Build a Mesh.

    ``axes`` maps axis name -> size in declaration order, e.g.
    ``{"dp": 2, "mp": 4}``; sizes must multiply to the device count.  A size of
    -1 means "whatever is left" (at most one).  Default: all devices on one
    data-parallel axis ``{"dp": -1}``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    axes = dict(axes) if axes else {"dp": -1}
    n = len(devices)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(f"axes {axes} do not cover {n} devices")
    grid = np.array(devices).reshape(sizes)
    return Mesh(grid, tuple(axes.keys()))


def make_host_mesh(
    axes: Mapping[str, int] | None = None,
    environ: Mapping[str, str] | None = None,
) -> Mesh:
    """Mesh over this process's addressable devices, ordered so that the
    trailing mesh axis walks the x-direction of the granted ICI block (device
    order from libtpu already follows the injected TPU_VISIBLE_CHIPS order)."""
    return make_mesh(axes, devices=jax.local_devices())
