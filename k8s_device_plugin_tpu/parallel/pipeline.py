"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

The reference has no parallelism code (SURVEY.md §2.4); this is the pipeline
member of the workload-side parallel layer (alongside tensor.py, ring.py,
ulysses.py, moe.py).  TPU-first shape: the pipeline is a *spatial* program —
every device holds ONE stage's parameters permanently, activations flow
stage-to-stage with ``lax.ppermute`` over neighbor ICI links, and the whole
schedule is a single ``lax.scan`` inside ``shard_map`` (static trip count
``n_micro + n_stages - 1``, no Python-level orchestration, one compiled
program).  Autodiff of the scan gives the classic GPipe backward schedule
for free — fill-drain bubbles and all — so a pipelined train step is just
``jax.grad`` around :func:`pipeline_apply`.

Zero-bubble/1F1B refinements trade this simplicity for schedule control;
GPipe is the right first rung and its bubble fraction
``(n_stages-1)/(n_micro+n_stages-1)`` vanishes with enough microbatches.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .ring import shard_map_unchecked


def stack_stage_params(stage_params: list) -> Any:
    """Stack per-stage parameter pytrees along a new leading stage axis.

    All stages must share a tree structure and leaf shapes (same layer type
    per stage — the GPipe regime)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *stage_params)


def mse_loss(y: jax.Array, t: jax.Array) -> jax.Array:
    """Default pipeline objective — the ONE shared definition (1F1B imports
    it too, so GPipe-vs-1F1B comparisons share an identical loss)."""
    return jnp.mean((y.astype(jnp.float32) - t.astype(jnp.float32)) ** 2)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    microbatches: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
) -> jax.Array:
    """Run ``microbatches`` through ``n_stages`` chained applications of
    ``stage_fn``, one stage per device along ``axis``.

    Args:
      stage_fn: ``(one_stage_params, x) -> y`` with ``y.shape == x.shape``
        (chainable stages; wrap embed/head outside the pipelined region).
      stacked_params: pytree whose leaves have leading dim ``n_stages``
        (:func:`stack_stage_params`); sharded over ``axis``.
      microbatches: ``[n_micro, ...]`` activation stream (replicated).
      mesh: mesh whose ``axis`` size equals ``n_stages``.

    Returns ``[n_micro, ...]`` outputs of the final stage, replicated.
    """
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(
            f"stacked_params lead dim {lead} != mesh axis {axis}={n_stages}"
        )

    def body(params_local, stream):
        # params_local leaves: [1, ...] (this device's stage); stream is the
        # full microbatch array (replicated input).
        params_me = jax.tree.map(lambda leaf: leaf[0], params_local)
        stage = jax.lax.axis_index(axis)
        ticks = n_micro + n_stages - 1
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        x_shape = stream.shape[1:]
        init_carry = (
            jnp.zeros(x_shape, stream.dtype),  # activation arriving from the left
            jnp.zeros((n_micro,) + x_shape, stream.dtype),  # output accumulator
        )

        def tick(carry, t):
            incoming, outputs = carry
            # Stage 0 ingests microbatch t (clamped; ticks past the stream
            # feed dead data that is never collected).
            feed = jax.lax.dynamic_index_in_dim(
                stream, jnp.minimum(t, n_micro - 1), 0, keepdims=False
            )
            x = jnp.where(stage == 0, feed, incoming)
            y = stage_fn(params_me, x)
            # The last stage completes microbatch t - (n_stages-1) at tick t.
            done_idx = t - (n_stages - 1)
            is_last = stage == n_stages - 1
            collect = jnp.logical_and(is_last, done_idx >= 0)
            outputs = jax.lax.cond(
                collect,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(done_idx, 0), 0
                ),
                lambda o: o,
                outputs,
            )
            incoming = jax.lax.ppermute(y, axis, fwd_perm)
            return (incoming, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, init_carry, jnp.arange(ticks))
        # Only the last stage holds real outputs; zero the rest and psum so
        # every device returns the replicated result.
        outputs = jnp.where(stage == n_stages - 1, outputs, 0)
        return jax.lax.psum(outputs, axis)

    in_specs = (
        jax.tree.map(lambda _: P(axis), stacked_params),
        P(),
    )
    fn = shard_map_unchecked(body, mesh=mesh, in_specs=in_specs, out_specs=P())
    return fn(stacked_params, microbatches)


def pipelined_loss_fn(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    axis: str = "pp",
    loss: Callable[[jax.Array, jax.Array], jax.Array] | None = None,
):
    """Build ``(stacked_params, microbatches, targets) -> scalar`` suitable
    for ``jax.grad``: pipeline forward, then mean loss over all microbatches
    (targets shaped like the pipeline output).  Default loss: MSE."""

    if loss is None:
        loss = mse_loss

    def fn(stacked_params, microbatches, targets):
        y = pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis)
        return loss(y, targets)

    return fn
