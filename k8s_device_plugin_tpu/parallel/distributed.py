"""Multi-host bootstrap: plugin-injected env -> jax.distributed process group.

The device-plugin API is node-local (one gRPC socket per kubelet), so the
reference has no cross-node path at all (SURVEY.md §2.4: its DaemonSet runs an
independent plugin per node and "parallelism is the workload's problem").
The TPU slice story instead rides on environment: the plugin's Allocate
response injects TPU_WORKER_ID / TPU_WORKER_HOSTNAMES (plugin/envs.py,
written from the node's /run/tpu drop-ins), and THIS module — imported by the
workload inside the pod — turns that env into a `jax.distributed` process
group over DCN, after which `jax.devices()` spans every chip in the slice and
XLA collectives ride ICI within a host and DCN across hosts.

Deployment analogue: deploy/k8s-job-resnet50-2host.yaml's two pods each call
`initialize()` first thing; worker 0's pod hosts the coordinator.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Mapping, Sequence

import jax

from .mesh import make_mesh

log = logging.getLogger(__name__)

# jax's conventional coordinator port; overridable via env.
DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class ProcessGroupConfig:
    """Arguments for jax.distributed.initialize, derived from injected env."""

    coordinator_address: str  # "host:port" of worker 0
    num_processes: int
    process_id: int


def process_group_from_env(
    environ: Mapping[str, str] | None = None,
    coordinator_port: int | None = None,
) -> ProcessGroupConfig | None:
    """Derive the slice's process group from the plugin-injected environment.

    Returns None when this pod is a single-host allocation (no
    TPU_WORKER_HOSTNAMES, or a one-host list) — jax needs no process group
    then.  Explicit JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID always win over the TPU_* derivation, so operators can
    override without touching the plugin.
    """
    environ = os.environ if environ is None else environ
    port = coordinator_port or int(
        environ.get("JAX_COORDINATOR_PORT", DEFAULT_COORDINATOR_PORT)
    )

    explicit = environ.get("JAX_COORDINATOR_ADDRESS")
    if explicit:
        num = int(environ.get("JAX_NUM_PROCESSES", "0"))
        if num <= 0:
            # Only a multi-host hostname list is a usable implicit count; a
            # sub-host/fragmented allocation never gets one injected
            # (plugin/envs.py), and silently defaulting to 1 would let worker
            # 0 "succeed" solo while its peers crash or hang.
            hostnames = _hostnames(environ)
            if len(hostnames) <= 1:
                raise ValueError(
                    "JAX_COORDINATOR_ADDRESS is set but JAX_NUM_PROCESSES is "
                    "not, and no multi-host TPU_WORKER_HOSTNAMES to infer from"
                )
            num = len(hostnames)
        pid_text = environ.get("JAX_PROCESS_ID", environ.get("TPU_WORKER_ID"))
        if pid_text is None:
            if num > 1:
                # Same duplicate-id-0 deadlock the implicit branch guards
                # against: every worker would claim process 0.
                raise ValueError(
                    "JAX_COORDINATOR_ADDRESS is set with "
                    f"JAX_NUM_PROCESSES={num} but neither JAX_PROCESS_ID nor "
                    "TPU_WORKER_ID identifies this worker"
                )
            pid = 0
        else:
            try:
                pid = int(pid_text)
            except ValueError:
                raise ValueError(
                    f"malformed JAX_PROCESS_ID/TPU_WORKER_ID {pid_text!r}"
                )
        if not 0 <= pid < num:
            raise ValueError(f"process id {pid} out of range for {num} processes")
        address = explicit if ":" in explicit else f"{explicit}:{port}"
        return ProcessGroupConfig(address, num, pid)

    hostnames = _hostnames(environ)
    if len(hostnames) <= 1:
        return None
    worker_id_text = environ.get("TPU_WORKER_ID", "0")
    try:
        worker_id = int(worker_id_text)
    except ValueError:
        # A malformed id must not silently become process 0: two processes
        # claiming id 0 deadlocks group formation until the timeout.
        raise ValueError(f"malformed TPU_WORKER_ID {worker_id_text!r}")
    if not 0 <= worker_id < len(hostnames):
        raise ValueError(
            f"TPU_WORKER_ID={worker_id} out of range for "
            f"{len(hostnames)} worker hostnames"
        )
    return ProcessGroupConfig(
        coordinator_address=f"{hostnames[0]}:{port}",
        num_processes=len(hostnames),
        process_id=worker_id,
    )


def _hostnames(environ: Mapping[str, str]) -> tuple[str, ...]:
    text = environ.get("TPU_WORKER_HOSTNAMES", "")
    return tuple(h.strip() for h in text.split(",") if h.strip())


_initialized = False


def initialize(
    environ: Mapping[str, str] | None = None,
    coordinator_port: int | None = None,
    **kwargs,
) -> bool:
    """Join the slice's jax.distributed process group if the injected env
    says this pod is part of a multi-host slice.  Idempotent; returns True
    iff a process group is (now) active.  kwargs pass through to
    jax.distributed.initialize (e.g. initialization_timeout)."""
    global _initialized
    if _initialized:
        return True
    config = process_group_from_env(environ, coordinator_port)
    if config is None:
        log.info("single-host allocation: no jax.distributed process group")
        return False
    log.info(
        "joining process group: coordinator=%s, process %d/%d",
        config.coordinator_address,
        config.process_id,
        config.num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=config.coordinator_address,
        num_processes=config.num_processes,
        process_id=config.process_id,
        **kwargs,
    )
    _initialized = True
    return True


def make_slice_mesh(
    axes: Mapping[str, int] | None = None,
    devices: Sequence | None = None,
):
    """Mesh over EVERY chip in the slice (all hosts), ordered host-major so
    that intra-host mesh axes map to ICI and the leading (cross-host) axis to
    DCN — shard batch over the leading axis, params/sequence over trailing
    ones, and collectives ride the fast links.  Single-host this equals
    make_mesh over local devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    devices.sort(key=lambda d: (d.process_index, d.id))
    return make_mesh(axes, devices=devices)
