"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context workloads shard the SEQUENCE over devices (the ``sp`` axis) so
no chip ever holds the full [seq, seq] score matrix or even the full kv.
Each device keeps its resident q shard and passes its k/v shard around the
ICI ring with ``lax.ppermute``; at every step it folds the visiting kv block
into a running online-softmax state (same math as the Pallas flash kernel in
ops/flash_attention.py, lifted from "one VMEM tile at a time" to "one
device's shard at a time").  After ``sp`` steps every q row has attended to
every kv position, with peak per-device memory O(local_seq²) and traffic
that rides neighbor-to-neighbor ICI links — never a global all-gather.

The reference has no distributed compute at all (SURVEY.md §2.4: parallelism
is "the workload's problem"); this module is the workload-side answer, built
on XLA collectives rather than any NCCL/MPI pattern.

Layering: `ring_attention` is the per-device body (call inside `shard_map`);
`ring_self_attention` wraps it for a global [batch, heads, seq, head_dim]
array over a Mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = float("-inf")

try:  # jax >= 0.8 spelling
    from jax import shard_map as _shard_map
except ImportError:  # older: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map_unchecked(body, *, mesh, in_specs, out_specs):
    """`shard_map` with varying-manual-axes checking off, across the JAX
    kwarg rename (`check_vma` >= 0.8, `check_rep` before).  The single home
    for this version shim — ulysses/pipeline/1F1B bodies all mix replicated
    inputs with per-device collectives, which the checker rejects."""
    try:
        return _shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:
        return _shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def expand_gqa_kv(q, k, v):
    """Expand grouped-query k/v to q's full head count (the fallback when a
    sharding axis can't split kv_heads — ring and Ulysses wrappers share it)."""
    group = q.shape[1] // k.shape[1]
    return jnp.repeat(k, group, axis=1), jnp.repeat(v, group, axis=1)


def _mark_varying(tree, axis_name):
    """Tag device-invariant values as varying over ``axis_name`` (shard_map
    tracks varying manual axes; scan carries must agree).  API drifted:
    pcast(to="varying") is current, pvary the older spelling."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(tree, axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(tree, axis_name)
    return tree  # pre-varying-types jax: no tagging needed


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    extra_varying: tuple = (),
) -> jax.Array:
    """Per-device ring attention body.

    Shapes are the LOCAL shards: [batch, heads, local_seq, head_dim], where
    global seq = local_seq * mesh.shape[axis_name] and shard i owns global
    positions [i*local_seq, (i+1)*local_seq).  Must run inside ``shard_map``
    (or ``pmap``) with ``axis_name`` bound.  ``extra_varying`` names any
    other manual axes the inputs are sharded over (dp/tp in a composed
    mesh), so the scan carry's varying-axis types line up.

    Grouped-query attention is native: k/v may carry ``kv_heads`` dividing
    q's ``heads``.  The rotating kv shard stays UN-expanded — ppermute
    traffic and kv memory scale with kv_heads, not heads (a group-factor
    ICI saving; q is reshaped to [b, kv_heads, group, seq, d] and the
    einsums contract against the shared kv head).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    batch, heads, seq_q, head_dim = q.shape
    kv_heads, seq_kv = k.shape[1], k.shape[2]
    if heads % kv_heads:
        raise ValueError(f"q heads {heads} not a multiple of kv heads {kv_heads}")
    group = heads // kv_heads
    f32 = jnp.float32
    qf = q.astype(f32).reshape(batch, kv_heads, group, seq_q, head_dim)

    rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_kv), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_kv), 1)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        src = (rank - t) % n  # which shard's kv we hold at this step
        # h = kv head, g = member of its q-head group: kv has no g axis, so
        # one kv shard serves the whole group (GQA-native, no repeat).
        s = (
            jnp.einsum(
                "bhgqd,bhkd->bhgqk",
                qf,
                k_blk.astype(f32),
                preferred_element_type=f32,
            )
            * sm_scale
        )
        if causal:
            row_g = rank * seq_q + rows
            col_g = src * seq_kv + cols
            s = jnp.where(row_g >= col_g, s, NEG_INF)

        # Online softmax fold (identical update rule to the flash kernel).
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        seen = m_new > NEG_INF
        p = jnp.where(seen, jnp.exp(s - jnp.where(seen, m_new, 0.0)), 0.0)
        alpha = jnp.where(seen, jnp.exp(jnp.where(seen, m - m_new, 0.0)), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhgqk,bhkd->bhgqd", p, v_blk.astype(f32), preferred_element_type=f32
        )

        # Rotate kv one hop around the ring (neighbor ICI traffic only).
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l_new, acc_new), None

    # The initial state is device-invariant; mark it as varying over every
    # manual axis the inputs vary over so the scan carry types line up
    # (shard_map tracks varying axes).
    m0, l0, acc0 = _mark_varying(
        (
            jnp.full((batch, kv_heads, group, seq_q, 1), NEG_INF, f32),
            jnp.zeros((batch, kv_heads, group, seq_q, 1), f32),
            jnp.zeros(qf.shape, f32),
        ),
        (axis_name,) + tuple(extra_varying),
    )
    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n)
    )
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output
    return (acc / l).astype(q.dtype).reshape(batch, heads, seq_q, head_dim)


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    sm_scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
) -> jax.Array:
    """Global-view wrapper: [batch, heads, seq, head_dim] arrays, sequence
    sharded over ``mesh`` axis ``axis``; returns the same global shape.

    ``batch_axis``/``head_axis`` name mesh axes the batch/head dims are
    already sharded over (dp / tp in a composed mesh) so the engine keeps
    those dims sharded instead of all-gathering them at the shard_map
    boundary — the ring only ever communicates over ``axis``.
    """
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"seq {q.shape[2]} not divisible by {axis}={n}")
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"q heads {q.shape[1]} not a multiple of kv heads {k.shape[1]}"
        )
    if head_axis and k.shape[1] != q.shape[1]:
        tp_size = mesh.shape[head_axis]
        if k.shape[1] % tp_size:
            # GQA kv heads can't shard over the tp axis (e.g. 2 kv heads on
            # tp=4): expand to full heads here — the pre-GQA behavior —
            # rather than failing in device_put with an opaque error.  The
            # ring stays GQA-native whenever the sharding allows it.
            k, v = expand_gqa_kv(q, k, v)
    spec = P(batch_axis, head_axis, axis, None)
    body = functools.partial(
        ring_attention,
        axis_name=axis,
        causal=causal,
        sm_scale=sm_scale,
        extra_varying=tuple(a for a in (batch_axis, head_axis) if a),
    )
    shard_mapped = _shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    sharding = NamedSharding(mesh, spec)
    return shard_mapped(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
