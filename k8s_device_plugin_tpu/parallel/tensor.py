"""Megatron-style tensor parallelism, expressed as GSPMD sharding rules.

The reference has no parallelism code at all (SURVEY.md §2.4: the plugin's
multi-device story ends at handing chips to pods); this module is the
workload-side layer that makes an N-chip allocation compute as one model.
TPU-first: no hand-written collectives — parameters are annotated with
NamedShardings over a ``tp`` mesh axis and XLA inserts the all-reduces, which
then ride the ICI links of the mesh block the plugin granted
(plugin/topology.py keeps grants ICI-contiguous for exactly this reason).

Layout (the classic Megatron column/row split, scaling-book recipe):

- attention query/key/value kernels  [embed, heads, head_dim] -> heads over tp
  (column-parallel: each chip owns a head group);
- attention out kernel  [heads, head_dim, embed] -> heads over tp
  (row-parallel: XLA all-reduces the partial outputs);
- MLP gate/up kernels  [embed, ffn] -> ffn over tp (column-parallel);
- MLP down kernel  [ffn, embed] -> ffn over tp (row-parallel);
- token embedding  [vocab, embed] -> vocab over tp;
- lm_head kernel  [embed, vocab] -> vocab over tp (sharded logits);
- norms / scalars replicated.

One forward+backward therefore needs exactly two all-reduces per block (attn
out + mlp down) plus the gradient reduce over ``dp`` — the minimal-comms
layout for a decoder block.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import replicated, shard_train_step

# (path regex, partition spec builder) — first match wins.  Specs are written
# for models/transformer.py's parameter tree; the fallthrough replicates, so
# foreign models degrade to pure data parallelism rather than breaking.
_TP_RULES: tuple[tuple[str, Any], ...] = (
    # MoE expert kernels (parallel/moe.py, bare-param leaves) first — their
    # names would otherwise suffix-match the dense gate/up/down rules below.
    (r"(^|/)experts_(gate|up)$", lambda tp: P("ep", None, tp)),
    (r"(^|/)experts_down$", lambda tp: P("ep", tp, None)),
    (r"(^|/)(query|key|value)/kernel$", lambda tp: P(None, tp, None)),
    (r"(^|/)out/kernel$", lambda tp: P(tp, None, None)),
    (r"(^|/)(gate|up)/kernel$", lambda tp: P(None, tp)),
    (r"(^|/)down/kernel$", lambda tp: P(tp, None)),
    (r"(^|/)embed/embedding$", lambda tp: P(tp, None)),
    (r"(^|/)lm_head/kernel$", lambda tp: P(None, tp)),
)


def _path_str(path) -> str:
    parts = []
    for entry in path:
        name = getattr(entry, "key", None)
        if name is None:
            name = getattr(entry, "name", None)
        if name is None:
            name = getattr(entry, "idx", "")
        parts.append(str(name))
    return "/".join(parts)


def tp_spec_for(
    path_str: str,
    leaf: Any,
    axis_sizes: Mapping[str, int],
    tp_axis: str = "tp",
) -> P:
    """PartitionSpec for one parameter leaf, by path rule.

    ``axis_sizes`` maps mesh axis name -> size (i.e. ``dict(mesh.shape)``).
    Falls back to replication when no rule matches, when the rule names a
    mesh axis the mesh does not have (e.g. expert kernels on a dp/tp-only
    mesh), or when a named dimension is not divisible by its axis size (tiny
    test configs on big meshes) — foreign models degrade to pure data
    parallelism rather than breaking.
    """
    for pattern, build in _TP_RULES:
        if re.search(pattern, path_str):
            spec = build(tp_axis)
            for dim, name in enumerate(spec):
                if name is None:
                    continue
                names = name if isinstance(name, tuple) else (name,)
                for axis in names:
                    if axis not in axis_sizes:
                        return P()
                    if dim >= getattr(leaf, "ndim", 0) or leaf.shape[dim] % axis_sizes[axis]:
                        return P()
            return spec
    return P()


def tp_param_sharding(params: Any, mesh: Mesh, tp_axis: str = "tp") -> Any:
    """NamedSharding tree for a transformer parameter pytree (or any pytree
    whose leaf paths end with the rule suffixes — optimizer moments mirror the
    param dict structure, so the same function shards them)."""
    axis_sizes = dict(mesh.shape)

    def rule(path, leaf):
        return NamedSharding(mesh, tp_spec_for(_path_str(path), leaf, axis_sizes, tp_axis))

    return jax.tree_util.tree_map_with_path(rule, params)


def tp_state_sharding(state: Any, mesh: Mesh, tp_axis: str = "tp") -> Any:
    """Sharding tree for models.train.TrainState under tensor parallelism.

    Optimizer moments are param-shaped subtrees whose key paths carry the same
    suffixes, so the path rules apply transitively; scalar counts fall through
    to replicated."""
    return type(state)(
        step=replicated(mesh),
        params=tp_param_sharding(state.params, mesh, tp_axis),
        opt_state=tp_param_sharding(state.opt_state, mesh, tp_axis),
        batch_stats=tp_param_sharding(state.batch_stats, mesh, tp_axis),
    )


def shard_train_step_tp(
    train_step,
    mesh: Mesh,
    state: Any,
    batch: Any,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
):
    """jit a train step with dp-sharded batch and tp-sharded parameters.

    Returns ``(jitted_step, placed_state, batch_shardings)`` like
    sharding.shard_train_step; gradients all-reduce over ``dp``, tensor
    partials all-reduce over ``tp`` — both inserted by XLA from the
    annotations, riding ICI.
    """
    return shard_train_step(
        train_step,
        mesh,
        state,
        batch,
        batch_axis=dp_axis,
        state_sharding_fn=lambda s: tp_state_sharding(s, mesh, tp_axis),
    )
