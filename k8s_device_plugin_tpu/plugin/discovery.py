"""TPU chip discovery from devfs + sysfs (+ node metadata drop-ins).

Replaces the reference's sysfs scanner (`countGPUDev`, reference main.go:50-81,
which globs /sys/class/kfd/kfd/topology/nodes/*/properties and counts
`simd_count > 0`) with a TPU-native inventory:

- chips are enumerated from ``/dev/accel*`` (the TPU VM chardev nodes, the
  analogue of the reference's /dev/kfd at main.go:84,144) cross-checked against
  ``/sys/class/accel/accel*``,
- per-chip PCI identity (vendor/device/numa/PCI address) is read from sysfs,
- host mesh bounds / accelerator type / multi-host worker metadata come from
  the environment or ``/run/tpu`` drop-in files written by node bootstrap.

Like the reference's ``topoRootParam`` test seam (main.go:52-56), every path
is resolved under an injectable filesystem root so tests (and the hermetic
demo) run against a fixture tree instead of the real ``/``.
"""

from __future__ import annotations

import glob
import logging
import os
import re
from dataclasses import dataclass
from typing import Mapping

from . import native
from .topology import bounds_str, chip_coords, host_bounds_for_count

log = logging.getLogger(__name__)

# PCI vendor id for Google accelerators.
GOOGLE_VENDOR_ID = "0x1ae0"

# Best-effort PCI device-id -> TPU generation table.  Detection never *relies*
# on this: accelerator type is taken from node metadata when present, and an
# unknown id degrades to generation=None with discovery still succeeding.
# Extend via the `extra_generations` argument to discover().
GENERATION_BY_DEVICE_ID: dict[str, str] = {
    "0x0062": "v4",
    "0x0063": "v5e",
    "0x0064": "v5p",
    "0x0065": "v6e",
}

# Node-metadata drop-in directory (under the injectable root).  Written by the
# node bootstrap / DaemonSet init container on real nodes; absent values fall
# back to environment variables and then to inference from the chip count.
TPU_METADATA_DIR = "run/tpu"

_ACCEL_DEV_RE = re.compile(r"accel(\d+)$")


@dataclass(frozen=True)
class TpuChip:
    """One discovered TPU chip (one /dev/accel* node)."""

    index: int  # host-local chip index (the N in /dev/accelN)
    device_path: str  # host devfs path, e.g. "/dev/accel0"
    vendor_id: str | None = None
    device_id: str | None = None
    pci_address: str | None = None
    numa_node: int | None = None
    generation: str | None = None

    @property
    def k8s_id(self) -> str:
        """Stable device ID advertised to the kubelet."""
        return f"tpu-{self.index}"


@dataclass(frozen=True)
class TpuHostInventory:
    """Everything discovery learned about this host's TPU complement."""

    chips: tuple[TpuChip, ...]
    host_bounds: tuple[int, int, int]  # chip-mesh bounds on this host
    accelerator_type: str | None  # e.g. "v5litepod-16"
    worker_id: int  # index of this host within its slice
    worker_hostnames: tuple[str, ...]  # all hosts in the slice, worker order

    @property
    def chip_count(self) -> int:
        return len(self.chips)

    @property
    def chips_per_host_bounds_str(self) -> str:
        return bounds_str(self.host_bounds)

    def chip_by_k8s_id(self, k8s_id: str) -> TpuChip:
        for chip in self.chips:
            if chip.k8s_id == k8s_id:
                return chip
        raise KeyError(k8s_id)

    def coords_of(self, chip: TpuChip) -> tuple[int, int, int]:
        return chip_coords(chip.index, self.host_bounds)


def _read_text(path: str) -> str | None:
    try:
        with open(path, "r") as f:
            return f.read().strip()
    except OSError:
        return None


def _read_int(path: str) -> int | None:
    text = _read_text(path)
    if text is None:
        return None
    try:
        return int(text, 0)
    except ValueError:
        return None


def _pci_address_from_uevent(uevent_path: str) -> str | None:
    text = _read_text(uevent_path)
    if not text:
        return None
    for line in text.splitlines():
        key, _, value = line.partition("=")
        if key.strip() == "PCI_SLOT_NAME":
            return value.strip()
    return None


def _sysfs_chip_info(root: str, index: int) -> dict:
    """Read one chip's identity from /sys/class/accel/accelN/device/."""
    dev_dir = os.path.join(root, "sys/class/accel", f"accel{index}", "device")
    return {
        "vendor_id": _read_text(os.path.join(dev_dir, "vendor")),
        "device_id": _read_text(os.path.join(dev_dir, "device")),
        "numa_node": _read_int(os.path.join(dev_dir, "numa_node")),
        "pci_address": _pci_address_from_uevent(os.path.join(dev_dir, "uevent")),
    }


def _metadata(root: str, name: str, environ: Mapping[str, str], env_key: str) -> str | None:
    """Node metadata: the /run/tpu drop-in file is authoritative; the env var
    is the fallback.  (A daemon inherits ambient env — e.g. a TPU-VM image's
    sitecustomize exporting TPU_* for every python process — so node-level
    files must win over whatever leaked into the pod environment.)"""
    value = _read_text(os.path.join(root, TPU_METADATA_DIR, name))
    if value:
        return value
    return environ.get(env_key) or None


def discover(
    root: str = "/",
    environ: Mapping[str, str] | None = None,
    extra_generations: Mapping[str, str] | None = None,
) -> TpuHostInventory:
    """Enumerate this host's TPU chips and slice metadata.

    ``root`` redirects all devfs/sysfs/metadata reads (the test seam).
    ``environ`` defaults to ``os.environ``.
    """
    environ = os.environ if environ is None else environ
    generations = dict(GENERATION_BY_DEVICE_ID)
    if extra_generations:
        generations.update(extra_generations)

    # --- chip enumeration: /dev/accel* is authoritative for existence -------
    # One readdir in C when libtpu_probe.so is loaded (plugin/native.py);
    # glob+regex is the fallback and the behavioral reference.
    indices: set[int] = set()
    prober = native.shared_prober()
    scanned = (
        prober.scan_accel_indices(os.path.join(root, "dev")) if prober else None
    )
    if scanned is not None:
        indices = set(scanned)
    else:
        for path in glob.glob(os.path.join(root, "dev", "accel[0-9]*")):
            m = _ACCEL_DEV_RE.search(os.path.basename(path))
            if m:
                indices.add(int(m.group(1)))
    # Cross-check sysfs: a chip the driver bound but whose dev node is missing
    # is worth logging (it will be advertised Unhealthy-from-birth territory,
    # but we do not advertise what cannot be mounted).
    sysfs_indices: set[int] = set()
    for path in glob.glob(os.path.join(root, "sys/class/accel", "accel[0-9]*")):
        m = _ACCEL_DEV_RE.search(os.path.basename(path))
        if m:
            sysfs_indices.add(int(m.group(1)))
    for missing_dev in sorted(sysfs_indices - indices):
        log.warning(
            "sysfs shows accel%d but /dev/accel%d is absent; not advertising it",
            missing_dev,
            missing_dev,
        )

    chips = []
    for index in sorted(indices):
        info = _sysfs_chip_info(root, index)
        vendor = info["vendor_id"]
        if vendor is not None and vendor.lower() != GOOGLE_VENDOR_ID:
            log.warning(
                "accel%d has non-Google vendor id %s; skipping", index, vendor
            )
            continue
        device_id = info["device_id"]
        chips.append(
            TpuChip(
                index=index,
                # Advertised host path is always the real devfs path; only
                # discovery reads go through `root`.
                device_path=f"/dev/accel{index}",
                vendor_id=vendor,
                device_id=device_id,
                pci_address=info["pci_address"],
                numa_node=info["numa_node"],
                generation=generations.get((device_id or "").lower()),
            )
        )

    # --- host/slice metadata ------------------------------------------------
    accelerator_type = _metadata(
        root, "accelerator-type", environ, "TPU_ACCELERATOR_TYPE"
    )

    bounds_text = _metadata(
        root, "chips-per-host-bounds", environ, "TPU_CHIPS_PER_HOST_BOUNDS"
    )
    # Bounds describe the PHYSICAL mesh, so infer them from the full index
    # span the driver exposed (sysfs ∪ devfs), not from how many chips
    # survived filtering: on a 2x2 host with accel2's dev node missing the
    # remaining chips {0,1,3} still sit at their 2x2 coordinates.
    physical_span = max(indices | sysfs_indices, default=-1) + 1
    if bounds_text:
        try:
            bx, by, bz = (int(v) for v in bounds_text.split(","))
            host_bounds = (bx, by, bz)
        except ValueError:
            log.warning("malformed chips-per-host bounds %r; inferring", bounds_text)
            host_bounds = host_bounds_for_count(physical_span)
    else:
        host_bounds = host_bounds_for_count(physical_span)

    worker_id_text = _metadata(root, "worker-id", environ, "TPU_WORKER_ID")
    try:
        worker_id = int(worker_id_text) if worker_id_text else 0
    except ValueError:
        worker_id = 0

    hostnames_text = _metadata(
        root, "worker-hostnames", environ, "TPU_WORKER_HOSTNAMES"
    )
    worker_hostnames = (
        tuple(h.strip() for h in hostnames_text.split(",") if h.strip())
        if hostnames_text
        else ()
    )

    inventory = TpuHostInventory(
        chips=tuple(chips),
        host_bounds=host_bounds,
        accelerator_type=accelerator_type,
        worker_id=worker_id,
        worker_hostnames=worker_hostnames,
    )
    log.info(
        "discovered %d TPU chip(s), bounds=%s, accelerator_type=%s, worker %d/%d",
        inventory.chip_count,
        inventory.chips_per_host_bounds_str,
        accelerator_type,
        worker_id,
        max(len(worker_hostnames), 1),
    )
    return inventory
