"""ctypes loader for the native probe helper (native/tpu_probe.c).

The daemon's two hot filesystem paths — the per-pulse per-chip health probe
and discovery's /dev scan — have a C implementation (libtpu_probe.so) so a
fast pulse costs a fixed few syscalls per chip with no Python-level file
object churn.  This module finds and wraps the library; every caller treats
it as optional and falls back to the pure-Python implementations
(plugin/health.py, plugin/discovery.py), which remain the behavioral
reference.  The reference plugin has no native component at all (SURVEY.md:
100% Go, kernel driver consumed via sysfs); this helper is our equivalent of
its compiled-binary probe path, built per SURVEY.md §7's guidance ("a tight
health-poll helper … as a small C++ tool").

Search order for the shared object:
1. ``TPU_PROBE_LIB`` environment variable (absolute path) — used by the
   container image, which builds the .so at image-build time;
2. ``native/libtpu_probe.so`` next to the repo checkout (dev/test builds);
3. give up and return None (callers use the Python path).
"""

from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess

log = logging.getLogger(__name__)

# Probe result codes — must mirror native/tpu_probe.c.
PROBE_OK = 0
PROBE_BUSY = 1
PROBE_MISSING = 2
PROBE_WRONGTYPE = 3
PROBE_OPENFAIL = 4

_ABI_VERSION = 1

_HEALTHY_CODES = frozenset({PROBE_OK, PROBE_BUSY})

_REPO_LIB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "libtpu_probe.so",
)
_SOURCE = os.path.join(os.path.dirname(_REPO_LIB), "tpu_probe.c")


class NativeProber:
    """Thin typed wrapper over a loaded libtpu_probe.so."""

    def __init__(self, lib: ctypes.CDLL, path: str):
        self.path = path
        self._lib = lib
        lib.tpu_probe_abi_version.restype = ctypes.c_int
        lib.tpu_probe_abi_version.argtypes = []
        lib.tpu_probe_device.restype = ctypes.c_int
        lib.tpu_probe_device.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.tpu_probe_devices.restype = None
        lib.tpu_probe_devices.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.tpu_scan_accel_indices.restype = ctypes.c_int
        lib.tpu_scan_accel_indices.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
        ]
        abi = lib.tpu_probe_abi_version()
        if abi != _ABI_VERSION:
            raise OSError(f"libtpu_probe ABI {abi} != expected {_ABI_VERSION}")

    def probe(self, device_path: str) -> tuple[int, int]:
        """Probe one device node; returns (code, errno)."""
        err = ctypes.c_int(0)
        code = self._lib.tpu_probe_device(
            device_path.encode(), ctypes.byref(err)
        )
        return code, err.value

    def probe_many(self, device_paths: list[str]) -> list[tuple[int, int]]:
        """Probe a batch of nodes in one FFI crossing."""
        n = len(device_paths)
        if n == 0:
            return []
        paths = (ctypes.c_char_p * n)(*[p.encode() for p in device_paths])
        codes = (ctypes.c_int * n)()
        errnos = (ctypes.c_int * n)()
        self._lib.tpu_probe_devices(paths, n, codes, errnos)
        return [(codes[i], errnos[i]) for i in range(n)]

    def scan_accel_indices(self, dev_dir: str) -> list[int] | None:
        """Chip indices of accelN entries under dev_dir; None if unreadable."""
        cap = 256
        out = (ctypes.c_int * cap)()
        n = self._lib.tpu_scan_accel_indices(dev_dir.encode(), out, cap)
        if n < 0:
            return None
        if n > cap:  # absurdly many chips: retry with an exact buffer
            cap = n
            out = (ctypes.c_int * cap)()
            n = self._lib.tpu_scan_accel_indices(dev_dir.encode(), out, cap)
            if n < 0:
                return None
        return sorted(out[i] for i in range(min(n, cap)))


def is_healthy_code(code: int) -> bool:
    """True iff a probe code means the chip should be advertised Healthy."""
    return code in _HEALTHY_CODES


def load_prober(lib_path: str | None = None) -> NativeProber | None:
    """Load libtpu_probe.so if available; None (with a debug log) otherwise."""
    candidates = (
        [lib_path]
        if lib_path
        else [os.environ.get("TPU_PROBE_LIB"), _REPO_LIB]
    )
    for candidate in candidates:
        if not candidate or not os.path.exists(candidate):
            continue
        try:
            return NativeProber(ctypes.CDLL(candidate), candidate)
        # AttributeError: the .so loaded but lacks the expected symbols
        # (stale/foreign library) — fall back, don't crash the daemon.
        except (OSError, AttributeError) as e:
            log.warning("failed to load native prober %s: %s", candidate, e)
    log.debug("native prober unavailable; using pure-Python probes")
    return None


_shared: tuple[NativeProber | None] | None = None


def shared_prober() -> NativeProber | None:
    """Process-wide prober, loaded once (None is also cached)."""
    global _shared
    if _shared is None:
        _shared = (load_prober(),)
    return _shared[0]


def build_probe_library(
    out_path: str, source: str = _SOURCE, cc: str | None = None
) -> str:
    """Compile tpu_probe.c into a shared object (dev/test convenience; the
    container image runs the same compile in its build stage)."""
    compiler = cc or shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
    if compiler is None:
        raise RuntimeError("no C compiler available to build libtpu_probe")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    subprocess.run(
        [compiler, "-O2", "-Wall", "-fPIC", "-shared", "-o", out_path, source],
        check=True,
        capture_output=True,
    )
    return out_path
