"""TPU environment injection for Allocate responses.

The reference injects NO environment (reference main.go:139-159 builds only
DeviceSpecs; isolation is left to the workload setting HIP_VISIBLE_DEVICES by
hand, k8s-pod-example-gpu.yaml:12-13).  For TPUs this env is the whole
multi-chip story (SURVEY.md §2.4/§5.8): libtpu forms the host-local ICI mesh
and jax.distributed coordinates across hosts purely from variables like these.
The plugin never moves tensor bytes — it tells the workload where its chips
sit so the workload's collectives ride ICI.
"""

from __future__ import annotations

from .discovery import TpuChip, TpuHostInventory
from .topology import SubMesh, bounds_str


def allocation_envs(
    inventory: TpuHostInventory,
    chips: list[TpuChip],
    sub_mesh: SubMesh | None,
) -> dict[str, str]:
    """Environment for one container allocated ``chips``.

    ``sub_mesh`` is the contiguous block the chips form, when one was found;
    None means a fragmented selection (the kubelet ignored or couldn't honor
    our GetPreferredAllocation advice).  libtpu requires SOME bounds covering
    the chip count, so the fallback claims a 1-D chain — which DOES assert
    links that may not physically exist; mesh bring-up may then run degraded
    or fail.  That is why GetPreferredAllocation steers allocations toward
    contiguous blocks in the first place, and why the fragmented path logs a
    warning rather than being treated as normal.
    """
    indices = sorted(c.index for c in chips)
    envs: dict[str, str] = {
        # Which of the host's chips belong to this container.
        "TPU_VISIBLE_CHIPS": ",".join(str(i) for i in indices),
        # The container must not ask the GCE metadata server for topology —
        # everything it needs is injected right here.
        "TPU_SKIP_MDS_QUERY": "true",
    }

    if len(chips) == inventory.chip_count and inventory.chip_count > 0:
        # Whole host: advertise the true host mesh bounds, and (if this host
        # is part of a multi-host slice) the worker coordinates jax.distributed
        # needs to stitch hosts together over DCN.
        envs["TPU_CHIPS_PER_HOST_BOUNDS"] = inventory.chips_per_host_bounds_str
        envs["TPU_WORKER_ID"] = str(inventory.worker_id)
        if inventory.worker_hostnames:
            envs["TPU_WORKER_HOSTNAMES"] = ",".join(inventory.worker_hostnames)
    elif sub_mesh is not None:
        # Sub-host contiguous block: the container sees a standalone mesh of
        # the block's bounds; it is always worker 0 of a single-host slice.
        envs["TPU_CHIPS_PER_HOST_BOUNDS"] = bounds_str(sub_mesh.bounds)
        envs["TPU_WORKER_ID"] = "0"
    else:
        # Fragmented fallback: claim a chain (see docstring — a known lie the
        # protocol forces; kept rare by GetPreferredAllocation).
        envs["TPU_CHIPS_PER_HOST_BOUNDS"] = bounds_str((len(chips), 1, 1))
        envs["TPU_WORKER_ID"] = "0"

    if inventory.accelerator_type:
        envs["TPU_ACCELERATOR_TYPE"] = inventory.accelerator_type
    return envs


def allocation_annotations(chips: list[TpuChip]) -> dict[str, str]:
    """Debugging/observability annotations mirrored onto the container."""
    return {
        "tpu.google.com/chips": ",".join(c.k8s_id for c in sorted(chips, key=lambda c: c.index)),
        "tpu.google.com/pci-addresses": ",".join(
            c.pci_address or "?" for c in sorted(chips, key=lambda c: c.index)
        ),
    }
