"""Per-pod TPU attribution: kubelet PodResources polling + allocation audit.

After PR 1 (tracing/metrics) and PR 2 (flight/incidents) the daemon can
say a chip is healthy and the engine can say a request was slow, but
nothing on the node can say WHICH POD OWNS WHICH CHIP — the join every
fleet dashboard and noisy-neighbor diagnosis needs (the host-side,
workload-attributed telemetry of arXiv:2510.16946).  This module closes
that gap:

- :class:`PodAttributionPoller` dials the kubelet's PodResources
  introspection socket (``pod-resources/kubelet.sock``, the v1
  ``PodResourcesLister`` service — hand-bound in kubelet/api.py, no
  protoc), builds the chip -> (namespace, pod, container) ownership map,
  and joins it with discovery/topology (chip index, ICI coords, NUMA,
  health) for ``GET /debug/pods``.
- Ownership becomes bounded-cardinality labeled series (at most one per
  chip on the host): ``tpu_chip_owner_info{device,namespace,pod,
  container}`` info-gauges and ``tpu_pod_chips{namespace,pod}`` counts,
  with series REMOVED via ``Gauge.remove`` the poll after a pod goes
  away — the same no-stale-series discipline the per-device health gauge
  applies on unplug.
- **Allocation-reconciliation audit**: the gRPC server records every
  device ID it granted into an :class:`AllocationLedger`; each poll
  diffs kubelet truth against the ledger.  Drift — kubelet attributing a
  chip the plugin never granted (``kind="ungranted"``), or a grant the
  kubelet never surfaced within the confirmation grace
  (``kind="unfulfilled"``) — increments
  ``tpu_attribution_drift_total{kind}``, records an
  ``attribution.drift`` flight event, and raises a direct anomaly
  incident (visible at ``/debug/incidents``).  A confirmed grant the
  kubelet later drops is the NORMAL pod-exit path (the device-plugin API
  has no Deallocate; kubelet truth is how the plugin learns of release).

Degrades gracefully by design: with no socket configured the poller is
never built; with the socket absent/unresponsive every poll sets
``tpu_podresources_up 0``, keeps the last-known (then aged-out) state,
and redials — the daemon otherwise runs exactly as before.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import Counter
from typing import Callable, Iterable, Mapping, Optional

import grpc

from ..kubelet.api import PodResourcesListerStub, prpb
from ..utils import failpoints
from ..utils.anomaly import AnomalyMonitor
from ..utils.flight import FlightRecorder
from ..utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)

DRIFT_METRIC = "plugin.attribution_drift"

# Dead pod-resources sockets flap with the kubelet; cap C-core's connect
# backoff so the first poll after a kubelet restart doesn't inherit a
# multi-second stall from the dead incarnation (same rationale as
# manager._register's registration channel).
_CHAN_OPTS = [
    ("grpc.initial_reconnect_backoff_ms", 100),
    ("grpc.max_reconnect_backoff_ms", 2000),
]


class AllocationLedger:
    """Device IDs the DevicePlugin's Allocate handed out, awaiting kubelet
    confirmation.

    The device-plugin API has no Deallocate, so the plugin can never
    observe a release directly — entries move ``granted`` -> ``confirmed``
    (the kubelet's PodResources view attributed the chip to a pod) ->
    gone (the kubelet dropped it: the pod exited), with the attribution
    poller driving both observation-side transitions.  Thread-safe:
    Allocate grants from gRPC worker threads while the poller reconciles.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        # device_id -> {"ts": grant time, "confirmed": bool, "owner": tuple|None}
        self._grants: dict[str, dict] = {}  # guarded by: _lock
        self.granted_total = 0
        self.released_total = 0

    def grant(self, device_ids: Iterable[str]) -> None:
        """Record one Allocate's device IDs (re-granting a released chip
        restarts its entry — pod churn reuses device IDs)."""
        now = self._clock()
        with self._lock:
            for device_id in device_ids:
                self.granted_total += 1
                self._grants[str(device_id)] = {
                    "ts": now, "confirmed": False, "owner": None,
                }

    def confirm(self, device_id: str, owner=None) -> None:
        """The kubelet attributed this grant to a pod."""
        with self._lock:
            entry = self._grants.get(device_id)
            if entry is not None:
                entry["confirmed"] = True
                if owner is not None:
                    entry["owner"] = tuple(owner)

    def release(self, device_id: str) -> bool:
        """Drop one grant (kubelet no longer attributes it — pod exited)."""
        with self._lock:
            if self._grants.pop(device_id, None) is None:
                return False
            self.released_total += 1
            return True

    def entry(self, device_id: str) -> Optional[dict]:
        with self._lock:
            entry = self._grants.get(device_id)
            return dict(entry) if entry is not None else None

    def granted(self) -> set[str]:
        with self._lock:
            return set(self._grants)

    def confirmed(self) -> set[str]:
        with self._lock:
            return {d for d, e in self._grants.items() if e["confirmed"]}

    def pending(self, older_than_s: float = 0.0) -> set[str]:
        """Unconfirmed grants at least ``older_than_s`` old — the audit's
        "granted but kubelet never surfaced it" candidates."""
        horizon = self._clock() - older_than_s
        with self._lock:
            return {
                d
                for d, e in self._grants.items()
                if not e["confirmed"] and e["ts"] <= horizon
            }

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            return {
                "granted_total": self.granted_total,
                "released_total": self.released_total,
                "outstanding": {
                    d: {
                        "age_s": round(now - e["ts"], 3),
                        "confirmed": e["confirmed"],
                        "owner": list(e["owner"]) if e["owner"] else None,
                    }
                    for d, e in sorted(self._grants.items())
                },
            }


class PodAttributionPoller:
    """Polls the kubelet PodResources API into ownership series, the
    ``/debug/pods`` join, and the allocation-reconciliation audit.

    ``metrics`` is a PluginMetrics (the attribution series live there so
    one registry serves /metrics); ``device_info`` is an optional no-arg
    callable returning ``{k8s_id: {...}}`` (TpuDevicePlugin.device_info)
    for the topology/health join.  Drive polls either via
    :meth:`start`/:meth:`stop` (daemon thread every ``interval_s``) or by
    calling :meth:`poll_once` directly (tests).
    """

    def __init__(
        self,
        socket_path: str,
        *,
        metrics=None,
        ledger: Optional[AllocationLedger] = None,
        resources: Iterable[str] = ("google.com/tpu",),
        device_info: Optional[Callable[[], Mapping[str, dict]]] = None,
        flight: Optional[FlightRecorder] = None,
        anomaly: Optional[AnomalyMonitor] = None,
        interval_s: float = 10.0,
        rpc_timeout_s: float = 5.0,
        confirm_grace_s: float = 60.0,
        allocatable_every: int = 30,
        clock: Callable[[], float] = time.monotonic,
    ):
        if metrics is None:
            from .server import PluginMetrics  # lazy: avoids a module cycle

            metrics = PluginMetrics(MetricsRegistry())
        self.socket_path = str(socket_path)
        self.metrics = metrics
        self.ledger = ledger
        self.resources = frozenset(resources)
        self._device_info = device_info
        self.flight = flight
        self.anomaly = anomaly
        self.interval_s = float(interval_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.confirm_grace_s = float(confirm_grace_s)
        self.allocatable_every = max(1, int(allocatable_every))
        self._clock = clock

        self._lock = threading.Lock()
        self._owners: dict[str, tuple[str, str, str]] = {}
        self._pod_counts: dict[tuple[str, str], int] = {}
        self._allocatable: set[str] = set()
        self._drift_active: dict[tuple[str, str], dict] = {}
        self._drift_by_kind: Counter = Counter()
        self._up: Optional[bool] = None  # None = never polled
        self.polls = 0
        self.failures = 0
        self._last_poll_s: Optional[float] = None

        self._channel: Optional[grpc.Channel] = None
        self._stub: Optional[PodResourcesListerStub] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- transport

    def _dial(self) -> PodResourcesListerStub:
        if self._stub is None:
            self._channel = grpc.insecure_channel(
                f"unix://{self.socket_path}", options=_CHAN_OPTS
            )
            self._stub = PodResourcesListerStub(self._channel)
        return self._stub

    def _hangup(self) -> None:
        channel, self._channel, self._stub = self._channel, None, None
        if channel is not None:
            channel.close()

    # ----------------------------------------------------------------- polls

    def poll_once(self) -> bool:
        """One poll: List (+ periodic GetAllocatableResources), apply the
        ownership diff, run the reconciliation audit.  Returns True when
        the kubelet answered; never raises on an absent/unresponsive
        socket (``tpu_podresources_up`` goes 0 instead)."""
        t0 = time.perf_counter()
        refresh_allocatable = self.polls % self.allocatable_every == 0
        self.polls += 1
        try:
            # Chaos seam (docs/chaos.md): error fails the poll exactly
            # like an unreachable socket (down-mark, redial, degraded
            # attribution); delay stretches the poll histogram.
            failpoints.fire("attribution.poll", socket=self.socket_path)
            stub = self._dial()
            listed = stub.List(
                prpb.ListPodResourcesRequest(), timeout=self.rpc_timeout_s
            )
            allocatable = (
                stub.GetAllocatableResources(
                    prpb.AllocatableResourcesRequest(),
                    timeout=self.rpc_timeout_s,
                )
                if refresh_allocatable
                else None
            )
        except (grpc.RpcError, OSError, failpoints.FailpointError) as e:
            self._mark_down(e)
            self.metrics.attribution_poll_seconds.observe(
                time.perf_counter() - t0
            )
            return False
        self._mark_up()
        owned: dict[str, tuple[str, str, str]] = {}
        for pod in listed.pod_resources:
            for container in pod.containers:
                for dev in container.devices:
                    if dev.resource_name not in self.resources:
                        continue
                    for device_id in dev.device_ids:
                        owned[device_id] = (pod.namespace, pod.name, container.name)
        with self._lock:
            if allocatable is not None:
                self._allocatable = {
                    device_id
                    for dev in allocatable.devices
                    if dev.resource_name in self.resources
                    for device_id in dev.device_ids
                }
                self.metrics.attribution_allocatable.set(len(self._allocatable))
            self._apply(owned)
            self._audit(owned)
            dt = time.perf_counter() - t0
            self._last_poll_s = dt
        self.metrics.attribution_poll_seconds.observe(dt)
        return True

    def _mark_down(self, error) -> None:
        self.failures += 1
        self.metrics.podresources_up.set(0)
        if self._up is not False:
            self._up = False
            code = error.code() if isinstance(error, grpc.RpcError) else error
            log.warning(
                "kubelet PodResources socket %s unreachable (%s); "
                "attribution degraded until it returns",
                self.socket_path,
                code,
            )
            if self.flight is not None:
                self.flight.record(
                    "podresources.down", socket=self.socket_path, error=str(code)
                )
        # Redial from scratch next poll: the socket identity changes
        # across kubelet restarts, exactly like kubelet.sock.
        self._hangup()

    def _mark_up(self) -> None:
        self.metrics.podresources_up.set(1)
        if self._up is not True:
            self._up = True
            if self.flight is not None:
                self.flight.record("podresources.up", socket=self.socket_path)

    # ----------------------------------------------------- ownership series

    def _apply(self, owned: Mapping[str, tuple[str, str, str]]) -> None:
        """Diff kubelet ownership against the published series: set on
        bind, remove on release (stale-ownership series must die with
        their pod, mirroring the device-health unplug pattern)."""
        m = self.metrics
        prev = self._owners
        for device_id in prev.keys() - owned.keys():
            ns, pod, container = prev[device_id]
            m.chip_owner.remove(
                device=device_id, namespace=ns, pod=pod, container=container
            )
            if self.flight is not None:
                self.flight.record(
                    "pod.release",
                    device=device_id, namespace=ns, pod=pod, container=container,
                )
        for device_id, owner in owned.items():
            old = prev.get(device_id)
            if old == owner:
                continue
            if old is not None:
                m.chip_owner.remove(
                    device=device_id,
                    namespace=old[0], pod=old[1], container=old[2],
                )
                if self.flight is not None:
                    self.flight.record(
                        "pod.release",
                        device=device_id,
                        namespace=old[0], pod=old[1], container=old[2],
                    )
            m.chip_owner.set(
                1,
                device=device_id,
                namespace=owner[0], pod=owner[1], container=owner[2],
            )
            if self.flight is not None:
                self.flight.record(
                    "pod.bind",
                    device=device_id,
                    namespace=owner[0], pod=owner[1], container=owner[2],
                )
        counts = Counter((ns, pod) for ns, pod, _ in owned.values())
        for ns, pod in self._pod_counts.keys() - counts.keys():
            m.pod_chips.remove(namespace=ns, pod=pod)
        for (ns, pod), n in counts.items():
            m.pod_chips.set(n, namespace=ns, pod=pod)
        self._pod_counts = dict(counts)
        self._owners = dict(owned)
        m.attribution_attributed.set(len(owned))

    # ------------------------------------------------------------ audit

    def _audit(self, owned: Mapping[str, tuple[str, str, str]]) -> None:
        """Diff kubelet truth against the Allocate ledger; meter drift."""
        if self.ledger is None:
            return
        for device_id, owner in owned.items():
            if self.ledger.entry(device_id) is None:
                self._raise_drift(
                    "ungranted",
                    device_id,
                    namespace=owner[0], pod=owner[1], container=owner[2],
                )
            else:
                self.ledger.confirm(device_id, owner=owner)
                self._clear_drift("unfulfilled", device_id)
        # Confirmed grants the kubelet dropped: the NORMAL release path
        # (pod exited) — reconcile the ledger, no drift.
        for device_id in self.ledger.confirmed() - owned.keys():
            self.ledger.release(device_id)
            if self.flight is not None:
                self.flight.record("ledger.release", device=device_id)
        # Grants the kubelet never surfaced within the grace window: the
        # kubelet lost (or never applied) an allocation it asked for.
        for device_id in self.ledger.pending(older_than_s=self.confirm_grace_s):
            if device_id not in owned:
                self._raise_drift("unfulfilled", device_id)
        # An ungranted chip the kubelet stopped reporting is no longer
        # drifting; re-arm so a recurrence fires again.
        for kind, device_id in list(self._drift_active):
            if kind == "ungranted" and device_id not in owned:
                self._clear_drift(kind, device_id)

    def _raise_drift(self, kind: str, device_id: str, **info) -> None:
        """Meter + record + raise ONE incident per (kind, device)
        activation; the counter/incident re-fire only after the
        condition clears and recurs, not every poll."""
        key = (kind, device_id)
        if key in self._drift_active:
            return
        # Field name is "drift", not "kind": flight events and incident
        # records both reserve "kind" for their own record type.
        detail = {"drift": kind, "device": device_id, **info}
        self._drift_active[key] = {"since": round(time.time(), 3), **detail}
        self._drift_by_kind[kind] += 1
        self.metrics.attribution_drift.inc(kind=kind)
        log.warning("attribution drift: %s", detail)
        if self.flight is not None:
            self.flight.record("attribution.drift", **detail)
        if self.anomaly is not None:
            self.anomaly.report(DRIFT_METRIC, observed=1.0, **detail)

    def _clear_drift(self, kind: str, device_id: str) -> None:
        self._drift_active.pop((kind, device_id), None)

    # ------------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """JSON body of ``GET /debug/pods``: the ownership map joined with
        discovery/topology/health, plus poller/ledger/drift state."""
        info: Mapping[str, dict] = {}
        if self._device_info is not None:
            try:
                info = self._device_info() or {}
            except Exception as e:  # join must not kill the snapshot
                info = {}
                log.debug("device_info join failed: %s", e)
        with self._lock:
            owners = dict(self._owners)
            allocatable = sorted(self._allocatable)
            drift_active = [dict(d) for d in self._drift_active.values()]
            drift_total = dict(self._drift_by_kind)
            last_poll_ms = (
                round(self._last_poll_s * 1e3, 3)
                if self._last_poll_s is not None
                else None
            )
            up = self._up
        pods: dict[tuple[str, str], dict] = {}
        for device_id, (ns, pod, container) in sorted(owners.items()):
            entry = pods.setdefault(
                (ns, pod), {"namespace": ns, "pod": pod, "containers": {}}
            )
            entry["containers"].setdefault(container, []).append(
                {"id": device_id, **info.get(device_id, {})}
            )
        return {
            "socket": self.socket_path,
            "up": up,
            "polls": self.polls,
            "failures": self.failures,
            "interval_s": self.interval_s,
            "last_poll_ms": last_poll_ms,
            "resources": sorted(self.resources),
            "allocatable": allocatable,
            "attributed_chips": len(owners),
            "pods": [
                {
                    "namespace": p["namespace"],
                    "pod": p["pod"],
                    "containers": [
                        {"container": c, "devices": devs}
                        for c, devs in sorted(p["containers"].items())
                    ],
                }
                for p in pods.values()
            ],
            "ledger": self.ledger.snapshot() if self.ledger is not None else None,
            "drift": {"active": drift_active, "total_by_kind": drift_total},
        }

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "PodAttributionPoller":
        assert self._thread is None
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpu-attribution", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        log.info(
            "pod attribution: polling %s every %.1fs (resources %s)",
            self.socket_path,
            self.interval_s,
            ",".join(sorted(self.resources)),
        )
        while True:
            try:
                self.poll_once()
            except Exception:
                # poll_once handles transport errors itself; anything
                # else is a bug that must not kill the poller thread.
                self.failures += 1
                log.exception("attribution poll failed")
            if self._stop_evt.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._hangup()
