"""Per-chip TPU health checking.

Upgrades the reference's node-global check (`simpleHealthCheck` at reference
main.go:83-91: one open() of /dev/kfd flips EVERY device between Healthy and
Unhealthy; its own TODOs at main.go:120-121 admit per-device health was never
built).  Here each chip is probed independently, and an operator/test
fault-injection seam is provided (the reference has none, SURVEY.md §5.3).
"""

from __future__ import annotations

import errno
import logging
import os
import stat
import time

from ..utils import failpoints
from . import native
from .discovery import TpuChip

log = logging.getLogger(__name__)

# Drop-in override directory (relative to the injectable root): writing
# "Unhealthy" to {root}/run/tpu/health/accelN force-fails chip N — operator
# kill-switch and fault-injection point for tests.
HEALTH_OVERRIDE_DIR = "run/tpu/health"

# open() errors that mean "the chip is there but busy" — a healthy condition:
# on a TPU VM, libtpu holds the accel fd exclusively while a workload runs.
_BUSY_ERRNOS = {errno.EBUSY, errno.EACCES, errno.EPERM}


class ChipHealthChecker:
    """Probes one chip at a time; the single-probe path is stateless.

    The probe itself runs through libtpu_probe.so when available (one C call
    per chip, see plugin/native.py) with this file's pure-Python sequence as
    the fallback and the behavioral reference; override files are always
    handled in Python (cold path).

    ``flap_threshold`` debounces the Healthy→Unhealthy transition on the
    sweep path (:meth:`check_many`): a currently-Healthy chip must fail
    ``flap_threshold`` CONSECUTIVE sweeps before it is reported
    Unhealthy (suppressed probes emit a ``health.flap_suppressed``
    flight event instead) — one transient open() error on a busy devfs
    must not flap the kubelet's device list.  Recovery is never
    debounced: one healthy probe flips a chip back immediately.  The
    default (1) preserves the old report-on-first-failure behavior;
    the CLI defaults to 2 (``--health-flap-threshold``).
    """

    def __init__(
        self,
        root: str = "/",
        prober: native.NativeProber | None | object = "auto",
        observe_sweep_seconds=None,
        flight=None,
        flap_threshold: int = 1,
    ):
        self._root = root
        # "auto" → process-wide shared library; None → force Python path.
        self._prober = native.shared_prober() if prober == "auto" else prober
        # Optional telemetry hook: called with the wall seconds of every
        # check_many sweep (cli.py wires it to the plugin's
        # tpu_plugin_health_sweep_seconds histogram AND the anomaly
        # monitor's sweep-duration baseline) — the ONE place sweep
        # latency is observed, whoever drives the sweep.
        self._observe_sweep = observe_sweep_seconds
        # Optional flight recorder (utils/flight.py): probe open()
        # failures are black-box events — the raw evidence behind a
        # health transition the plugin later streams.
        self._flight = flight
        if flap_threshold < 1:
            raise ValueError(
                f"flap_threshold must be >= 1, got {flap_threshold}"
            )
        self._flap_threshold = int(flap_threshold)
        self._fail_streak: dict[str, int] = {}  # k8s_id -> consecutive fails
        self._last_reported: dict[str, bool] = {}  # k8s_id -> last sweep verdict

    def _inject(self, chip: TpuChip) -> bool | None:
        """The ``health.probe`` failpoint (docs/chaos.md): ``flap``
        forces alternating probe failures (True = fault active →
        Unhealthy probe), ``delay`` slows the sweep (feeding the sweep-
        duration anomaly baseline), ``error`` raises out of the sweep
        (the wedged-sysfs shape — the heartbeat's poll-failure counter
        catches it).  Returns the forced verdict or None."""
        hit = failpoints.fire("health.probe", device=chip.k8s_id)
        if hit is not None and hit.mode == "flap" and hit.value:
            if self._flight is not None:
                self._flight.record(
                    "health.probe_failure",
                    device=chip.device_path,
                    error=f"failpoint health.probe (trigger {hit.n})",
                )
            return False
        return None

    def _override(self, chip: TpuChip) -> bool | None:
        path = os.path.join(self._root, HEALTH_OVERRIDE_DIR, f"accel{chip.index}")
        try:
            with open(path, "r") as f:
                text = f.read().strip().lower()
        except OSError:
            return None
        return text not in {"unhealthy", "0", "false"}

    def check(self, chip: TpuChip) -> bool:
        """True iff the chip's PROBE came back healthy (stateless — the
        sweep-path debounce lives in :meth:`check_many`)."""
        # State transitions are logged once by the caller (poll_once), so the
        # per-probe path stays quiet even at high pulse rates.
        override = self._override(chip)
        if override is not None:
            return override
        injected = self._inject(chip)
        if injected is not None:
            return injected

        dev_path = os.path.join(self._root, chip.device_path.lstrip("/"))
        if self._prober is not None:
            code, err = self._prober.probe(dev_path)
            return self._classify(dev_path, code, err)
        try:
            st = os.stat(dev_path)
        except OSError:
            return False  # device node vanished
        # On a real node this is a chardev; fixture trees use regular files.
        if not (stat.S_ISCHR(st.st_mode) or stat.S_ISREG(st.st_mode)):
            return False
        try:
            fd = os.open(dev_path, os.O_RDONLY | os.O_NONBLOCK)
        except OSError as e:
            if e.errno in _BUSY_ERRNOS:
                return True  # exclusively held by a workload: alive and in use
            log.warning("open(%s) failed: %s", dev_path, e)
            if self._flight is not None:
                self._flight.record(
                    "health.probe_failure", device=dev_path, error=str(e)
                )
            return False
        else:
            os.close(fd)
            return True

    def _classify(self, dev_path: str, code: int, err: int) -> bool:
        if code == native.PROBE_OPENFAIL:
            log.warning(
                "open(%s) failed: %s", dev_path, os.strerror(err) if err else err
            )
            if self._flight is not None:
                self._flight.record(
                    "health.probe_failure",
                    device=dev_path,
                    error=os.strerror(err) if err else str(err),
                )
        return native.is_healthy_code(code)

    def check_many(self, chips: tuple[TpuChip, ...] | list[TpuChip]) -> dict[str, bool]:
        """Health of a whole inventory, k8s_id -> healthy.  With the native
        prober this is ONE FFI crossing for every non-overridden chip (the
        per-pulse hot path of the daemon); otherwise it loops check()."""
        t0 = time.perf_counter()
        try:
            return self._debounce(self._check_many(chips))
        finally:
            if self._observe_sweep is not None:
                self._observe_sweep(time.perf_counter() - t0)

    def _check_many(self, chips) -> dict[str, bool]:
        result: dict[str, bool] = {}
        if self._prober is None:
            return {chip.k8s_id: self.check(chip) for chip in chips}
        batched: list[tuple[TpuChip, str]] = []
        for chip in chips:
            override = self._override(chip)
            if override is not None:
                result[chip.k8s_id] = override
                continue
            injected = self._inject(chip)
            if injected is not None:
                result[chip.k8s_id] = injected
                continue
            batched.append(
                (chip, os.path.join(self._root, chip.device_path.lstrip("/")))
            )
        codes = self._prober.probe_many([path for _, path in batched])
        for (chip, path), (code, err) in zip(batched, codes):
            result[chip.k8s_id] = self._classify(path, code, err)
        return result

    def _debounce(self, raw: dict[str, bool]) -> dict[str, bool]:
        """Suppress Healthy→Unhealthy flips until ``flap_threshold``
        consecutive failed sweeps (recovery passes through untouched).
        One transient probe error must not cycle a chip through the
        kubelet's device list — unhealthy devices get their workloads
        evicted, which is far more expensive than one skipped pulse."""
        out: dict[str, bool] = {}
        for k8s_id, healthy in raw.items():
            if healthy:
                self._fail_streak.pop(k8s_id, None)
                self._last_reported[k8s_id] = True
                out[k8s_id] = True
                continue
            streak = self._fail_streak.get(k8s_id, 0) + 1
            self._fail_streak[k8s_id] = streak
            # A never-seen chip debounces from Healthy: its first failing
            # sweep could be the same transient this gate exists for.
            was = self._last_reported.get(k8s_id, True)
            if was and streak < self._flap_threshold:
                out[k8s_id] = True
                log.info(
                    "suppressing health flap of %s (%d/%d consecutive "
                    "failures)",
                    k8s_id, streak, self._flap_threshold,
                )
                if self._flight is not None:
                    self._flight.record(
                        "health.flap_suppressed",
                        device=k8s_id,
                        streak=streak,
                        threshold=self._flap_threshold,
                    )
            else:
                out[k8s_id] = False
                self._last_reported[k8s_id] = False
        # Unplugged chips leave no stale streak state behind.
        for k8s_id in set(self._fail_streak) - raw.keys():
            del self._fail_streak[k8s_id]
        for k8s_id in set(self._last_reported) - raw.keys():
            del self._last_reported[k8s_id]
        return out
