"""Per-chip TPU health checking.

Upgrades the reference's node-global check (`simpleHealthCheck` at reference
main.go:83-91: one open() of /dev/kfd flips EVERY device between Healthy and
Unhealthy; its own TODOs at main.go:120-121 admit per-device health was never
built).  Here each chip is probed independently, and an operator/test
fault-injection seam is provided (the reference has none, SURVEY.md §5.3).
"""

from __future__ import annotations

import errno
import logging
import os
import stat
import time

from . import native
from .discovery import TpuChip

log = logging.getLogger(__name__)

# Drop-in override directory (relative to the injectable root): writing
# "Unhealthy" to {root}/run/tpu/health/accelN force-fails chip N — operator
# kill-switch and fault-injection point for tests.
HEALTH_OVERRIDE_DIR = "run/tpu/health"

# open() errors that mean "the chip is there but busy" — a healthy condition:
# on a TPU VM, libtpu holds the accel fd exclusively while a workload runs.
_BUSY_ERRNOS = {errno.EBUSY, errno.EACCES, errno.EPERM}


class ChipHealthChecker:
    """Probes one chip at a time; stateless between calls.

    The probe itself runs through libtpu_probe.so when available (one C call
    per chip, see plugin/native.py) with this file's pure-Python sequence as
    the fallback and the behavioral reference; override files are always
    handled in Python (cold path).
    """

    def __init__(
        self,
        root: str = "/",
        prober: native.NativeProber | None | object = "auto",
        observe_sweep_seconds=None,
        flight=None,
    ):
        self._root = root
        # "auto" → process-wide shared library; None → force Python path.
        self._prober = native.shared_prober() if prober == "auto" else prober
        # Optional telemetry hook: called with the wall seconds of every
        # check_many sweep (cli.py wires it to the plugin's
        # tpu_plugin_health_sweep_seconds histogram AND the anomaly
        # monitor's sweep-duration baseline) — the ONE place sweep
        # latency is observed, whoever drives the sweep.
        self._observe_sweep = observe_sweep_seconds
        # Optional flight recorder (utils/flight.py): probe open()
        # failures are black-box events — the raw evidence behind a
        # health transition the plugin later streams.
        self._flight = flight

    def _override(self, chip: TpuChip) -> bool | None:
        path = os.path.join(self._root, HEALTH_OVERRIDE_DIR, f"accel{chip.index}")
        try:
            with open(path, "r") as f:
                text = f.read().strip().lower()
        except OSError:
            return None
        return text not in {"unhealthy", "0", "false"}

    def check(self, chip: TpuChip) -> bool:
        """True iff the chip should be advertised Healthy."""
        # State transitions are logged once by the caller (poll_once), so the
        # per-probe path stays quiet even at high pulse rates.
        override = self._override(chip)
        if override is not None:
            return override

        dev_path = os.path.join(self._root, chip.device_path.lstrip("/"))
        if self._prober is not None:
            code, err = self._prober.probe(dev_path)
            return self._classify(dev_path, code, err)
        try:
            st = os.stat(dev_path)
        except OSError:
            return False  # device node vanished
        # On a real node this is a chardev; fixture trees use regular files.
        if not (stat.S_ISCHR(st.st_mode) or stat.S_ISREG(st.st_mode)):
            return False
        try:
            fd = os.open(dev_path, os.O_RDONLY | os.O_NONBLOCK)
        except OSError as e:
            if e.errno in _BUSY_ERRNOS:
                return True  # exclusively held by a workload: alive and in use
            log.warning("open(%s) failed: %s", dev_path, e)
            if self._flight is not None:
                self._flight.record(
                    "health.probe_failure", device=dev_path, error=str(e)
                )
            return False
        else:
            os.close(fd)
            return True

    def _classify(self, dev_path: str, code: int, err: int) -> bool:
        if code == native.PROBE_OPENFAIL:
            log.warning(
                "open(%s) failed: %s", dev_path, os.strerror(err) if err else err
            )
            if self._flight is not None:
                self._flight.record(
                    "health.probe_failure",
                    device=dev_path,
                    error=os.strerror(err) if err else str(err),
                )
        return native.is_healthy_code(code)

    def check_many(self, chips: tuple[TpuChip, ...] | list[TpuChip]) -> dict[str, bool]:
        """Health of a whole inventory, k8s_id -> healthy.  With the native
        prober this is ONE FFI crossing for every non-overridden chip (the
        per-pulse hot path of the daemon); otherwise it loops check()."""
        t0 = time.perf_counter()
        try:
            return self._check_many(chips)
        finally:
            if self._observe_sweep is not None:
                self._observe_sweep(time.perf_counter() - t0)

    def _check_many(self, chips) -> dict[str, bool]:
        result: dict[str, bool] = {}
        if self._prober is None:
            return {chip.k8s_id: self.check(chip) for chip in chips}
        batched: list[tuple[TpuChip, str]] = []
        for chip in chips:
            override = self._override(chip)
            if override is not None:
                result[chip.k8s_id] = override
            else:
                batched.append(
                    (chip, os.path.join(self._root, chip.device_path.lstrip("/")))
                )
        codes = self._prober.probe_many([path for _, path in batched])
        for (chip, path), (code, err) in zip(batched, codes):
            result[chip.k8s_id] = self._classify(path, code, err)
        return result
