"""Idle-chip self-test sweep — the plugin half of the active
correctness plane (ISSUE 17; fleet half in router/prober.py).

The health checker (plugin/health.py) answers "is the chip *there*":
open() probes catch a vanished device node or a wedged driver, but a
chip that computes *wrong answers* opens fine.  Silent data corruption
is a real fleet-scale accelerator failure mode (Exploration of TPUs
for AI Applications, PAPERS.md), and the worst time to learn about it
is after the kubelet placed a training pod on the sick chip.

:class:`SelftestSweeper` closes that gap host-side: chips the
:class:`~.attribution.AllocationLedger` shows **unallocated** get a
periodic deterministic matmul-checksum probe.  The expected checksum
is computed once per process from the same seeded inputs (pure
function — no golden files); a probe whose checksum diverges is a
failed self-test.  ``fail_threshold`` consecutive failures (one blip
never acts, same K-consecutive discipline as the canary prober)
quarantine the chip by writing the health checker's own override file
(``run/tpu/health/accelN`` — plugin/health.py reads it first), so the
very next health sweep reports the chip Unhealthy, the kubelet pulls
it from the allocatable list, and no pod ever lands on it.  Recovery
is manual on purpose: a chip that failed a deterministic checksum
stays fenced until an operator removes the override file (the triage
table in docs/operations.md).

Busy chips are never probed — the ledger is the arbiter — so the
sweep costs nothing on a saturated node and the probe can never race
a workload for the device.

jax-free, clock-injectable; the ``selftest.probe`` failpoint
(docs/chaos.md) corrupts or fails probes for chaos scenarios, and
``probe_fn`` is the unit-test seam.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
import zlib
from typing import Callable, Optional

import numpy as np

from ..utils import failpoints
from .health import HEALTH_OVERRIDE_DIR

log = logging.getLogger(__name__)

FAILPOINT_PROBE = "selftest.probe"

# Probe workload shape: big enough that a bad MAC unit has work to
# corrupt, small enough to stay invisible next to a health sweep.
_PROBE_DIM = 64


def matmul_checksum(seed: int = 0, dim: int = _PROBE_DIM) -> int:
    """Deterministic matmul-checksum probe: seeded integer matrices,
    exact int64 product, crc32 of the result bytes.  Integer on
    purpose — bit-exact on every host, no float tolerance to hide a
    flipped bit in."""
    rng = np.random.default_rng(seed)
    a = rng.integers(-128, 128, size=(dim, dim), dtype=np.int64)
    b = rng.integers(-128, 128, size=(dim, dim), dtype=np.int64)
    return zlib.crc32(np.ascontiguousarray(a @ b).tobytes())


@dataclasses.dataclass
class SelftestConfig:
    """Tunables for :class:`SelftestSweeper` (CLI: ``--selftest-*``)."""

    # Seconds between idle sweeps.
    interval_s: float = 60.0
    # Consecutive checksum failures before the chip is quarantined.
    fail_threshold: int = 2
    # Quarantine policy: write the health override file (the kubelet
    # stops placing pods) — False = observe-only (incidents still fire).
    quarantine: bool = True
    # Probe workload seed (rotated per sweep so a stuck-at fault that
    # happens to checksum clean on one input still gets caught).
    seeds: tuple = (0, 1, 2, 3)

    def __post_init__(self):
        if self.fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        if not self.seeds:
            raise ValueError("at least one probe seed required")


class _ChipTrack:
    __slots__ = (
        "verdict", "fail_streak", "probes", "failures", "quarantined",
    )

    def __init__(self):
        self.verdict = None
        self.fail_streak = 0
        self.probes = 0
        self.failures = 0
        self.quarantined = False


class SelftestSweeper:
    """Periodic idle-chip correctness sweep.

    ``inventory_fn`` returns the chips to consider (TpuChip tuples from
    discovery); ``busy_fn`` returns the set of k8s_ids currently
    allocated (cli.py passes ``ledger.granted`` — granted includes
    confirmed); ``probe_fn(chip, seed)`` returns the probe checksum
    (defaults to :func:`matmul_checksum`, which ignores the chip — the
    unit-test and future-device seam)."""

    def __init__(
        self,
        inventory_fn: Callable[[], tuple],
        busy_fn: Callable[[], set],
        *,
        config: Optional[SelftestConfig] = None,
        root: str = "/",
        metrics=None,
        flight=None,
        anomaly=None,
        probe_fn=None,
        now=time.perf_counter,
    ):
        self.cfg = config or SelftestConfig()
        self._inventory_fn = inventory_fn
        self._busy_fn = busy_fn
        self._root = root
        self._metrics = metrics
        self._flight = flight
        self._anomaly = anomaly
        self._probe_fn = probe_fn
        self._now = now
        self._lock = threading.Lock()
        self._tracks: dict[str, _ChipTrack] = {}
        # Expected checksum per seed, computed once on first use from
        # the same pure function the probes run — self-golden.
        self._expected: dict[int, int] = {}
        self.sweeps = 0
        self.quarantines = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ probes

    def _record(self, kind: str, **fields) -> None:
        if self._flight is not None:
            self._flight.record(kind, **fields)

    def _count(self, device: str, verdict: str) -> None:
        m = getattr(self._metrics, "selftests", None)
        if m is not None:
            m.inc(device=device, verdict=verdict)

    def _expected_for(self, seed: int) -> int:
        got = self._expected.get(seed)
        if got is None:
            got = matmul_checksum(seed)
            self._expected[seed] = got
        return got

    def _probe(self, chip, seed: int) -> int:
        """One probe checksum, through the chaos seam: arming
        ``selftest.probe.<k8s_id>=corrupt`` (or the bare site) flips
        bits of ONE chip's result — the injected-SDC ground truth the
        chaos scenario scores detection against; ``error`` raises
        (probe machinery broken, not a sick chip)."""
        hit = failpoints.fire_scoped(
            FAILPOINT_PROBE, scope=chip.k8s_id, device=chip.k8s_id
        )
        if self._probe_fn is not None:
            checksum = int(self._probe_fn(chip, seed))
        else:
            checksum = matmul_checksum(seed)
        if hit is not None and hit.mode == "corrupt":
            nbytes = int(hit.arg) if hit.arg else 1
            checksum ^= (1 << (8 * nbytes)) - 1
        return checksum

    def _quarantine(self, chip) -> None:
        """Write the health checker's override file: the next health
        sweep reports the chip Unhealthy and the kubelet stops placing
        pods on it — the same kill-switch an operator would use, so
        recovery tooling and triage are identical."""
        path = os.path.join(
            self._root, HEALTH_OVERRIDE_DIR, f"accel{chip.index}"
        )
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                f.write("Unhealthy")
        except OSError as e:  # pragma: no cover - bad root in prod only
            log.error("selftest quarantine write failed for %s: %s",
                      chip.k8s_id, e)
            self._record(
                "selftest.quarantine_failed", device=chip.k8s_id,
                error=str(e),
            )
            return
        self.quarantines += 1
        g = getattr(self._metrics, "selftest_quarantined", None)
        if g is not None:
            g.set(1, device=chip.k8s_id)
        self._record("selftest.quarantine", device=chip.k8s_id, path=path)
        log.warning(
            "chip %s quarantined by self-test (override %s)",
            chip.k8s_id, path,
        )

    def poll_once(self) -> dict:
        """One sweep over the currently-idle inventory; returns
        {k8s_id: verdict} (verdicts: pass/fail/skip_busy/error).  The
        unit-test driving seam — production calls it from the daemon
        thread."""
        cfg = self.cfg
        seed = cfg.seeds[self.sweeps % len(cfg.seeds)]
        expected = self._expected_for(seed)
        try:
            chips = tuple(self._inventory_fn())
            busy = set(self._busy_fn())
        except Exception as e:
            self._record("selftest.sweep_error", error=str(e))
            self.sweeps += 1
            return {}
        verdicts: dict[str, str] = {}
        for chip in chips:
            with self._lock:
                track = self._tracks.setdefault(chip.k8s_id, _ChipTrack())
            if chip.k8s_id in busy:
                # The ledger is the arbiter: never race a workload for
                # the device, never charge a busy chip a probe.
                verdicts[chip.k8s_id] = "skip_busy"
                with self._lock:
                    track.verdict = "skip_busy"
                self._count(chip.k8s_id, "skip_busy")
                continue
            t0 = self._now()
            try:
                checksum = self._probe(chip, seed)
            except Exception as e:
                verdicts[chip.k8s_id] = "error"
                with self._lock:
                    track.verdict = "error"
                self._count(chip.k8s_id, "error")
                self._record(
                    "selftest.probe_error", device=chip.k8s_id,
                    error=str(e),
                )
                continue
            h = getattr(self._metrics, "selftest_seconds", None)
            if h is not None:
                h.observe(self._now() - t0)
            with self._lock:
                track.probes += 1
                if checksum == expected:
                    track.fail_streak = 0
                    track.verdict = "pass"
                    verdicts[chip.k8s_id] = "pass"
                else:
                    track.fail_streak += 1
                    track.failures += 1
                    track.verdict = "fail"
                    verdicts[chip.k8s_id] = "fail"
                streak = track.fail_streak
                quarantined = track.quarantined
            self._count(chip.k8s_id, verdicts[chip.k8s_id])
            if verdicts[chip.k8s_id] != "fail":
                continue
            self._record(
                "selftest.checksum_mismatch", device=chip.k8s_id,
                seed=seed, streak=streak, got=checksum, want=expected,
            )
            if streak == cfg.fail_threshold:
                # The confirmed sick-chip incident: once per episode.
                self._record(
                    "selftest.fail", device=chip.k8s_id, streak=streak
                )
                if self._anomaly is not None:
                    self._anomaly.report(
                        "selftest.fail", observed=float(streak),
                        device=chip.k8s_id,
                    )
            if streak >= cfg.fail_threshold and cfg.quarantine \
                    and not quarantined:
                self._quarantine(chip)
                with self._lock:
                    track.quarantined = True
        self.sweeps += 1
        return verdicts

    def snapshot(self) -> dict:
        """The ``GET /debug/selftest`` body (any thread)."""
        with self._lock:
            chips = {
                k8s_id: {
                    "verdict": t.verdict,
                    "fail_streak": t.fail_streak,
                    "probes": t.probes,
                    "failures": t.failures,
                    "quarantined": t.quarantined,
                }
                for k8s_id, t in self._tracks.items()
            }
        return {
            "sweeps": self.sweeps,
            "quarantines": self.quarantines,
            "chips": chips,
            "config": {
                "interval_s": self.cfg.interval_s,
                "fail_threshold": self.cfg.fail_threshold,
                "quarantine": self.cfg.quarantine,
                "seeds": list(self.cfg.seeds),
            },
        }

    # --------------------------------------------------------- lifecycle

    def start(self) -> "SelftestSweeper":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tpu-selftest", daemon=True
        )
        self._thread.start()
        self._record("selftest.started", interval_s=self.cfg.interval_s)
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.poll_once()
            except Exception as e:  # pragma: no cover - belt and braces
                self._record("selftest.sweep_error", error=str(e))

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self._record("selftest.stopped", sweeps=self.sweeps)
