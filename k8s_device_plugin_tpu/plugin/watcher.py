"""Filesystem watch on the kubelet's Registration socket.

The reference watches /var/lib/kubelet/device-plugins/ with fsnotify and
restarts/stops its plugin servers when kubelet.sock is created/removed
(reference dpm/manager.go:53-55,73-84) — that re-registration dance is the
entire kubelet-restart recovery story.  Python has no stdlib inotify, so this
module binds the Linux inotify syscalls via ctypes, with a stat-polling
fallback for non-Linux/odd environments.  The polling path additionally
detects in-place socket recreation (inode change without a visible delete),
which the real kubelet is known to produce (reference dpm/manager.go:79-80).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import os
import select
import struct
import threading
from typing import Callable

log = logging.getLogger(__name__)

IN_CREATE = 0x00000100
IN_MOVED_TO = 0x00000080
IN_DELETE = 0x00000200
IN_DELETE_SELF = 0x00000400
IN_IGNORED = 0x00008000
IN_NONBLOCK = 0x00000800

_EVENT_HEADER = struct.Struct("iIII")  # wd, mask, cookie, len


def _load_libc():
    try:
        libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6", use_errno=True)
        # Probe the symbols we need.
        libc.inotify_init1
        libc.inotify_add_watch
        return libc
    except (OSError, AttributeError):
        return None


class KubeletSocketWatcher(threading.Thread):
    """Fires callbacks when ``socket_name`` appears/disappears in ``directory``.

    ``on_create`` / ``on_remove`` run on the watcher thread; keep them short
    (the manager just sets events / kicks a restart).
    """

    def __init__(
        self,
        directory: str,
        socket_name: str,
        on_create: Callable[[], None],
        on_remove: Callable[[], None],
        poll_interval: float = 1.0,
    ):
        super().__init__(name="kubelet-sock-watcher", daemon=True)
        self._dir = directory
        self._name = socket_name
        self._path = os.path.join(directory, socket_name)
        self._on_create = on_create
        self._on_remove = on_remove
        self._poll_interval = poll_interval
        self._stopped = threading.Event()
        # Set once the watch is armed; callers that must not miss an event
        # (e.g. a kubelet restarting right after plugin startup) wait on it.
        self.ready = threading.Event()

    def stop(self) -> None:
        self._stopped.set()

    # ------------------------------------------------------------------ run

    def run(self) -> None:
        libc = _load_libc()
        fire_initial = False
        while libc is not None and not self._stopped.is_set():
            try:
                self._run_inotify(libc, fire_initial)
                # Watch lost (e.g. the watched directory itself was deleted
                # and recreated by a kubelet reinstall): poll for the dir to
                # come back, then re-arm inotify.
                if not self._stopped.is_set():
                    log.warning("inotify watch on %s lost; re-arming", self._dir)
                    while not self._stopped.wait(self._poll_interval):
                        if os.path.isdir(self._dir):
                            break
                    # The socket may have been recreated before the new watch
                    # armed; have the next arm treat "already present" as a
                    # create.
                    fire_initial = True
                    continue
                return
            except OSError as e:
                log.warning("inotify unavailable (%s); falling back to polling", e)
                break
        if not self._stopped.is_set():
            self._run_polling()

    def _run_inotify(self, libc, fire_initial: bool = False) -> None:
        fd = libc.inotify_init1(IN_NONBLOCK)
        if fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1")
        try:
            wd = libc.inotify_add_watch(
                fd,
                self._dir.encode(),
                IN_CREATE | IN_MOVED_TO | IN_DELETE | IN_DELETE_SELF,
            )
            if wd < 0:
                raise OSError(ctypes.get_errno(), f"inotify_add_watch({self._dir})")
            log.info("watching %s via inotify", self._dir)
            # Also run the inode-change poll: inotify alone misses an in-place
            # bind over an existing path.
            last_ino = self._stat_ino()
            self.ready.set()
            if fire_initial and last_ino is not None:
                log.info("%s present after watch re-arm; treating as created", self._path)
                self._on_create()
            while not self._stopped.is_set():
                readable, _, _ = select.select([fd], [], [], self._poll_interval)
                if readable:
                    for name, mask in self._drain(fd):
                        if mask & (IN_DELETE_SELF | IN_IGNORED):
                            # The watched directory itself went away; the
                            # kernel has dropped the watch.  Return so run()
                            # can re-arm once the dir reappears.
                            if self._stat_ino() is not None or last_ino is not None:
                                self._on_remove()
                            return
                        if name != self._name:
                            continue
                        if mask & (IN_CREATE | IN_MOVED_TO):
                            log.info("%s created", self._path)
                            last_ino = self._stat_ino()
                            self._on_create()
                        elif mask & IN_DELETE:
                            log.info("%s removed", self._path)
                            last_ino = None
                            self._on_remove()
                else:
                    # Inode poll backstop: catches an in-place re-bind AND a
                    # create that raced the watch arming (None -> inode).
                    ino = self._stat_ino()
                    if ino != last_ino:
                        if ino is None:
                            log.info("%s removed (poll)", self._path)
                            self._on_remove()
                        else:
                            log.info("%s (re)created (poll)", self._path)
                            self._on_create()
                    last_ino = ino
        finally:
            os.close(fd)

    def _drain(self, fd: int):
        try:
            data = os.read(fd, 4096)
        except BlockingIOError:
            return
        offset = 0
        while offset + _EVENT_HEADER.size <= len(data):
            _wd, mask, _cookie, name_len = _EVENT_HEADER.unpack_from(data, offset)
            offset += _EVENT_HEADER.size
            name = data[offset : offset + name_len].split(b"\0", 1)[0].decode()
            offset += name_len
            yield name, mask

    def _run_polling(self) -> None:
        log.info("watching %s via stat polling", self._path)
        last_ino = self._stat_ino()
        self.ready.set()
        while not self._stopped.wait(self._poll_interval):
            ino = self._stat_ino()
            if ino == last_ino:
                continue
            if ino is None:
                log.info("%s removed", self._path)
                self._on_remove()
            else:
                log.info("%s (re)created", self._path)
                self._on_create()
            last_ino = ino

    def _stat_ino(self) -> int | None:
        try:
            return os.stat(self._path).st_ino
        except OSError:
            return None
