"""tpu-device-plugin entry point.

≙ reference main() (main.go:189-220): parse flags, wire discovery + health +
server + manager, install signal handlers, block.  The reference's single
`-pulse` flag (main.go:190-193) is kept by name; everything the reference
hard-coded is a flag here.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys

from ..kubelet import constants
from ..utils import failpoints
from ..utils import flight as flight_mod
from ..utils.anomaly import AnomalyMonitor
from ..utils.logging import setup_logging
from ..utils.metrics import MetricsServer
from ..utils.spans import SpanRecorder
from . import discovery
from .attribution import AllocationLedger, PodAttributionPoller
from .health import ChipHealthChecker
from .manager import DEFAULT_ENDPOINT, PluginManager
from .server import DEFAULT_REGISTRY, RESOURCE, TpuDevicePlugin, default_plugin_metrics

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-device-plugin",
        description="Kubernetes device plugin advertising google.com/tpu chips",
    )
    p.add_argument(
        "--pulse",
        type=float,
        default=0.0,
        help="seconds between health polls (0 disables the heartbeat, as in the reference)",
    )
    p.add_argument(
        "--root",
        default="/",
        help="filesystem root for devfs/sysfs/metadata reads (tests/fixtures use a tempdir)",
    )
    p.add_argument(
        "--plugin-dir",
        default=constants.DEVICE_PLUGIN_PATH,
        help="kubelet device-plugin socket directory",
    )
    p.add_argument("--endpoint", default=DEFAULT_ENDPOINT, help="plugin socket filename")
    p.add_argument("--resource", default=RESOURCE, help="resource name to advertise")
    p.add_argument(
        "--resources",
        default="",
        help="comma-separated resource names sharing one namespace (e.g. "
        "'google.com/tpu,google.com/tpu-slice'): serve ALL of them through "
        "the multi-resource lifecycle manager (one plugin server + "
        "registration each, ≙ the reference's generic dpm lister contract). "
        "Overrides --resource/--endpoint.",
    )
    p.add_argument(
        "--require-chips",
        action="store_true",
        help="exit immediately if no TPU chips are discovered (default: serve an empty list; "
        "the reference instead probed /sys/class/kfd before announcing, main.go:211-217)",
    )
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--json-logs", action="store_true", help="emit JSON log lines")
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="serve Prometheus /metrics (+ /healthz, /debug/devices, "
        "/debug/incidents, /debug/flight, /debug/spans) on this port "
        "(0 disables; beyond-reference observability, SURVEY.md §5.5/§7)",
    )
    p.add_argument(
        "--flight-ring",
        type=int,
        default=2048,
        help="capacity of the flight-recorder event ring (utils/flight.py: "
        "registrations, ListAndWatch updates, Allocates, health "
        "transitions) dumped on SIGUSR2/exit and served at /debug/flight",
    )
    p.add_argument(
        "--pod-resources-socket",
        default="",
        help="kubelet PodResources socket to poll for per-pod chip "
        "attribution (typically "
        f"{constants.POD_RESOURCES_SOCKET}; the DaemonSet yamls mount "
        "it).  Empty disables; an absent/unresponsive socket degrades "
        "gracefully (tpu_podresources_up 0) and the daemon otherwise "
        "runs exactly as without the flag",
    )
    p.add_argument(
        "--pod-resources-interval",
        type=float,
        default=10.0,
        help="seconds between PodResources attribution polls "
        "(ownership series, /debug/pods, allocation-reconciliation "
        "audit)",
    )
    p.add_argument(
        "--dump-dir",
        default=flight_mod.default_dump_dir() or "",
        help="directory for flight-recorder dumps: `kill -USR2 <pid>` "
        "writes one on demand, and the daemon writes a final one at exit "
        "when this is set (default: $TPU_PLUGIN_DUMP_DIR; the DaemonSet "
        "yamls mount /run/tpu/dump here)",
    )
    p.add_argument(
        "--dump-budget-mb",
        type=int,
        default=0,
        help="retention budget (MiB) for --dump-dir, shared by flight "
        "dumps and postmortem bundles (utils/postmortem.py): after "
        "every write the oldest entries are pruned until the directory "
        "fits (0 = unbounded)",
    )
    p.add_argument(
        "--health-flap-threshold",
        type=int,
        default=2,
        help="consecutive failed health sweeps before a Healthy chip is "
        "reported Unhealthy (debounce: one transient probe error must "
        "not flap the kubelet's device list and evict workloads; "
        "suppressed flips emit health.flap_suppressed flight events; "
        "1 restores report-on-first-failure)",
    )
    p.add_argument(
        "--selftest-interval",
        type=float,
        default=0.0,
        help="seconds between idle-chip self-test sweeps "
        "(plugin/selftest.py, docs/operations.md \"Active probing\"): "
        "chips the allocation ledger shows unallocated get a "
        "deterministic matmul-checksum probe; fail-threshold "
        "consecutive divergences fire a selftest.fail incident and "
        "quarantine the chip through the health override file before "
        "the kubelet places a pod on it.  0 disables (default)",
    )
    p.add_argument(
        "--selftest-fail-threshold",
        type=int,
        default=2,
        help="consecutive self-test checksum failures before the "
        "incident + quarantine (one blip never quarantines)",
    )
    p.add_argument(
        "--selftest-quarantine",
        type=int,
        choices=[0, 1],
        default=1,
        help="quarantine policy: 1 = a confirmed self-test failure "
        "writes the run/tpu/health/accelN override (next health sweep "
        "reports Unhealthy); 0 = observe-only (incidents still fire)",
    )
    p.add_argument(
        "--failpoints",
        default="",
        help="arm chaos failpoints: 'name=mode[:arg][*count];...' with "
        "modes error/delay/hang/flap (utils/failpoints.py; catalog in "
        "docs/chaos.md).  Adds to any $TPU_FAILPOINTS arming; every "
        "trigger is a flight event, armed state at /debug/failpoints",
    )
    return p


def _build_multi_manager(args, new_plugin):
    """--resources path: every listed name gets its own plugin server and
    registration under one shared kubelet watch (plugin/resources.py)."""
    from .resources import MultiResourceManager, StaticLister

    pairs = []
    for full in args.resources.split(","):
        full = full.strip()
        if "/" not in full:
            raise SystemExit(
                f"--resources entries must be namespace/name, got {full!r}"
            )
        pairs.append(tuple(full.rsplit("/", 1)))
    namespaces = {ns for ns, _ in pairs}
    if len(namespaces) != 1:
        # The dpm lister contract scopes one manager to one namespace
        # (reference dpm/lister.go:13-16).
        raise SystemExit(
            f"--resources must share one namespace, got {sorted(namespaces)}"
        )

    lister = StaticLister(
        [name for _, name in pairs],
        lambda name: new_plugin(),
        namespace=namespaces.pop(),
    )
    return MultiResourceManager(
        lister, plugin_dir=args.plugin_dir, pulse=args.pulse
    )


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, args.json_logs)

    # Forensics layer, one set per process shared by every resource's
    # plugin: the flight-recorder black box (registered so `kill -USR2`
    # and exit dump it — utils/flight.py), the anomaly monitor over
    # Allocate latency and health-sweep duration, and the daemon span
    # ring fed by timed_rpc (utils/tracing.py).
    box = flight_mod.register(
        flight_mod.FlightRecorder(capacity=args.flight_ring, name="daemon")
    )
    flight_mod.install_dump_handlers(args.dump_dir or None)
    if args.dump_budget_mb:
        flight_mod.set_dump_budget(args.dump_budget_mb * 1024 * 1024)
    # Chaos failpoints (utils/failpoints.py): env arming first, then the
    # flag adds/overrides; triggers become flight events in the same box
    # the detectors attach to incidents — injected cause and detected
    # effect land in one forensic timeline.
    failpoints.set_flight(box)
    failpoints.arm_from_env()
    if args.failpoints:
        failpoints.arm_spec(args.failpoints)
    monitor = AnomalyMonitor(
        flight=box,
        on_incident=lambda m: default_plugin_metrics().incidents.inc(metric=m),
    )
    monitor.configure(
        "plugin.health_sweep_seconds", warmup=30, z_threshold=6.0, sustain=3
    )
    spans = SpanRecorder(capacity=512)
    # One allocation ledger per process, shared by every resource's
    # plugin: Allocate grants land here and the attribution poller diffs
    # kubelet PodResources truth against it (plugin/attribution.py).
    ledger = AllocationLedger()

    def observe_sweep(dt: float) -> None:
        # One hook, two sinks: the Prometheus histogram operators scrape
        # and the EWMA baseline that turns a sustained slow sweep (wedged
        # sysfs/devfs) into an incident record.
        default_plugin_metrics().health_sweep_seconds.observe(dt)
        monitor.observe("plugin.health_sweep_seconds", dt)

    def new_plugin() -> TpuDevicePlugin:
        return TpuDevicePlugin(
            discover=lambda: discovery.discover(root=args.root),
            health_checker=ChipHealthChecker(
                root=args.root,
                observe_sweep_seconds=observe_sweep,
                flight=box,
                flap_threshold=args.health_flap_threshold,
            ),
            metrics=default_plugin_metrics(),
            flight=box,
            anomaly=monitor,
            spans=spans,
            ledger=ledger,
        )

    debug_endpoints = {
        "/debug/incidents": monitor.snapshot,
        "/debug/flight": box.snapshot,
        "/debug/failpoints": failpoints.snapshot,
        # ?rid=<trace id> filters to one request's tree (the trace
        # assembler's live mode; MetricsServer hands query-declaring
        # callables the parsed query dict).
        "/debug/spans": lambda query: spans.dump(
            trace_id=(query.get("rid") or [None])[0]
        ),
    }
    if args.resources:
        # Multi-resource mode builds one plugin per resource inside the
        # manager; probe inventory directly rather than via a throwaway plugin.
        inventory = discovery.discover(root=args.root)
        served = args.resources
    else:
        plugin = new_plugin()
        inventory = plugin.inventory  # discovery already ran once in the ctor
        served = args.resource
        # Device snapshot next to /metrics: what this node is advertising.
        debug_endpoints["/debug/devices"] = plugin.debug_state
    if args.require_chips and inventory.chip_count == 0:
        log.error("no TPU chips found under %s and --require-chips is set", args.root)
        return 1
    if args.resources:
        manager = _build_multi_manager(args, new_plugin)
    else:
        manager = PluginManager(
            plugin,
            plugin_dir=args.plugin_dir,
            endpoint=args.endpoint,
            resource=args.resource,
            pulse=args.pulse,
        )
    poller = None
    if args.pod_resources_socket:
        # Per-pod chip attribution + allocation-reconciliation audit.
        # In multi-resource mode the plugins live inside the manager, so
        # the /debug/pods join degrades to device IDs without the
        # discovery/topology fields; the single-resource daemon joins
        # the full chip info.
        resource_names = (
            {p.strip() for p in args.resources.split(",") if p.strip()}
            if args.resources
            else {args.resource}
        )
        poller = PodAttributionPoller(
            args.pod_resources_socket,
            metrics=default_plugin_metrics(),
            ledger=ledger,
            resources=resource_names,
            device_info=None if args.resources else plugin.device_info,
            flight=box,
            anomaly=monitor,
            interval_s=args.pod_resources_interval,
        )
        debug_endpoints["/debug/pods"] = poller.snapshot
    selftest = None
    if args.selftest_interval > 0:
        # Idle-chip self-test sweep (plugin/selftest.py): the plugin
        # half of the active correctness plane.  Discovery re-runs per
        # sweep (chips unplug); the ledger arbitrates idleness.
        from .selftest import SelftestConfig, SelftestSweeper

        selftest = SelftestSweeper(
            lambda: discovery.discover(root=args.root).chips,
            ledger.granted,
            config=SelftestConfig(
                interval_s=args.selftest_interval,
                fail_threshold=args.selftest_fail_threshold,
                quarantine=bool(args.selftest_quarantine),
            ),
            root=args.root,
            metrics=default_plugin_metrics(),
            flight=box,
            anomaly=monitor,
        )
        debug_endpoints["/debug/selftest"] = selftest.snapshot

    def daemon_state() -> dict:
        # The daemon's /debug/state-equivalent: the non-query debug
        # surfaces joined into one snapshot — what the fleet postmortem
        # collector pulls alongside flight/spans/metrics, and what the
        # local capture hook writes as state.json.
        state = {"component": "daemon", "served": served}
        for path, fn in debug_endpoints.items():
            if path in ("/debug/flight", "/debug/spans", "/debug/state"):
                continue  # own evidence files / this aggregate itself
            try:
                state[path.rsplit("/", 1)[-1]] = fn()
            except Exception as e:
                state[path.rsplit("/", 1)[-1]] = {"error": str(e)}
        return state

    debug_endpoints["/debug/state"] = daemon_state
    if args.dump_dir:
        # Incident-triggered local postmortem capture
        # (utils/postmortem.py): every incident the monitor emits —
        # slow health sweeps, attribution drift, self-test failures —
        # snapshots the daemon's forensic state into a content-addressed
        # bundle under --dump-dir, debounced per cause metric.
        from ..utils.postmortem import PostmortemCapture

        capture = PostmortemCapture(
            "daemon",
            args.dump_dir,
            flight=box,
            spans=spans,
            registry=DEFAULT_REGISTRY,
            state_fn=daemon_state,
            budget_bytes=(
                args.dump_budget_mb * 1024 * 1024
                if args.dump_budget_mb
                else None
            ),
        )
        monitor.add_listener(capture.on_incident)
        debug_endpoints["/debug/postmortem"] = capture.snapshot
    metrics_server = None

    def _on_signal(signum, _frame):
        log.info("received %s; shutting down", signal.Signals(signum).name)
        manager.shutdown()

    try:
        for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGQUIT):
            signal.signal(sig, _on_signal)
    except ValueError:
        # Not on the main interpreter thread (hermetic tests drive main() from
        # a worker thread); shutdown is then delivered via manager.shutdown().
        log.debug("not on main thread; skipping signal handlers")

    log.info(
        "starting %s plugin: %d chip(s), plugin_dir=%s, pulse=%.1fs",
        served,
        inventory.chip_count,
        args.plugin_dir,
        args.pulse,
    )
    try:
        if args.metrics_port:
            metrics_server = MetricsServer(
                DEFAULT_REGISTRY,
                port=args.metrics_port,
                health=manager.alive,
                debug=debug_endpoints,
            )
            metrics_server.start()
            log.info(
                "metrics on :%d/metrics (+ %s)",
                metrics_server.port,
                " ".join(sorted(debug_endpoints)),
            )
        if poller is not None:
            poller.start()
        if selftest is not None:
            selftest.start()
        manager.run()
    finally:
        if selftest is not None:
            selftest.stop()
        if poller is not None:
            poller.stop()
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
