"""The google.com/tpu DevicePlugin gRPC service.

TPU-native re-design of the reference's `Plugin` (reference main.go:38-159),
fixing its known defects rather than reproducing them:

- `ListAndWatch` REBUILDS the full device list on every update (the reference
  appends to the previous slice, growing duplicates each heartbeat —
  main.go:126-132) and re-runs discovery on each poll, so hot-(un)plug is
  reflected (the reference counts once at stream start — main.go:105).
- Health is per-chip (health.py) instead of one node-global /dev/kfd open
  flipping everything (main.go:83-91,122).
- `Allocate` HONORS the requested device IDs, mounting exactly those
  /dev/accel* nodes and injecting mesh/topology env (the reference ignores the
  IDs and grants /dev/kfd + all of /dev/dri with no env — main.go:139-159).
- `GetPreferredAllocation` steers the kubelet toward ICI-contiguous sub-meshes
  (no reference analogue; the topology-data-but-no-code gap of SURVEY.md §2.4).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

import grpc

from ..kubelet import constants
from ..kubelet.api import pb
from .discovery import TpuChip, TpuHostInventory
from .envs import allocation_annotations, allocation_envs
from .health import ChipHealthChecker
from .topology import SubMesh, select_contiguous

log = logging.getLogger(__name__)

RESOURCE_NAMESPACE = "google.com"
RESOURCE_NAME = "tpu"
RESOURCE = f"{RESOURCE_NAMESPACE}/{RESOURCE_NAME}"


class TpuDevicePlugin:
    """DevicePlugin servicer for one node's TPU chips.

    Thread-safe: the manager's heartbeat thread calls :meth:`poll_once` while
    kubelet RPCs arrive on gRPC worker threads; every ListAndWatch stream
    waits on one condition variable and re-sends a full snapshot whenever the
    state version advances.
    """

    def __init__(
        self,
        discover: Callable[[], TpuHostInventory],
        health_checker: ChipHealthChecker,
    ):
        self._discover = discover
        self._health_checker = health_checker
        self._cond = threading.Condition()
        self._version = 0
        self._epoch = 0  # bumped by interrupt_streams(); streams die on change
        self._inventory: TpuHostInventory | None = None
        self._health: dict[str, bool] = {}  # k8s_id -> healthy
        self.poll_once()

    def interrupt_streams(self) -> None:
        """End every open ListAndWatch stream promptly (server shutdown /
        restart); streams opened afterwards are unaffected."""
        with self._cond:
            self._epoch += 1
            self._cond.notify_all()

    # ------------------------------------------------------------------ state

    def poll_once(self) -> bool:
        """Re-discover chips and re-check health; returns True if anything
        changed (and wakes every ListAndWatch stream)."""
        inventory = self._discover()
        health = {
            chip.k8s_id: self._health_checker.check(chip) for chip in inventory.chips
        }
        with self._cond:
            changed = (
                self._inventory is None
                or health != self._health
                or [c.k8s_id for c in inventory.chips]
                != [c.k8s_id for c in self._inventory.chips]
            )
            self._inventory = inventory
            self._health = health
            if changed:
                self._version += 1
                self._cond.notify_all()
        if changed:
            log.info(
                "device state v%d: %s",
                self._version,
                {k: ("Healthy" if v else "Unhealthy") for k, v in health.items()},
            )
        return changed

    def _snapshot(self) -> tuple[int, TpuHostInventory, dict[str, bool]]:
        with self._cond:
            assert self._inventory is not None
            return self._version, self._inventory, dict(self._health)

    @property
    def inventory(self) -> TpuHostInventory:
        """Latest discovered inventory (for CLI/observability consumers)."""
        return self._snapshot()[1]

    def _device_list(self, inventory: TpuHostInventory, health: dict[str, bool]):
        devices = []
        for chip in inventory.chips:
            dev = pb.Device(
                ID=chip.k8s_id,
                health=constants.HEALTHY if health.get(chip.k8s_id) else constants.UNHEALTHY,
            )
            if chip.numa_node is not None and chip.numa_node >= 0:
                dev.topology.nodes.add(ID=chip.numa_node)
            devices.append(dev)
        return devices

    # ------------------------------------------------------------- RPC: admin

    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(
            pre_start_required=False,
            get_preferred_allocation_available=True,
        )

    def PreStartContainer(self, request, context):
        return pb.PreStartContainerResponse()

    # ------------------------------------------------------------ RPC: stream

    def ListAndWatch(self, request, context):
        with self._cond:
            epoch = self._epoch
        version, inventory, health = self._snapshot()
        log.info("ListAndWatch stream opened (v%d, %d chips)", version, inventory.chip_count)
        yield pb.ListAndWatchResponse(devices=self._device_list(inventory, health))
        while True:
            with self._cond:
                # Wake on state change or interrupt; time out periodically to
                # notice a disconnected kubelet and end the stream cleanly.
                while self._version == version and self._epoch == epoch:
                    if not self._cond.wait(timeout=5.0):
                        if not context.is_active():
                            log.info("ListAndWatch stream closed by peer")
                            return
                if self._epoch != epoch:
                    log.info("ListAndWatch stream interrupted (server stopping)")
                    return
                version = self._version
                inventory, health = self._inventory, dict(self._health)
            if not context.is_active():
                return
            yield pb.ListAndWatchResponse(devices=self._device_list(inventory, health))

    # --------------------------------------------------- RPC: preferred alloc

    def GetPreferredAllocation(self, request, context):
        _, inventory, _ = self._snapshot()
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            preferred = self._prefer(
                inventory,
                available=list(creq.available_deviceIDs),
                must_include=list(creq.must_include_deviceIDs),
                size=creq.allocation_size,
            )
            resp.container_responses.add(deviceIDs=preferred)
        return resp

    def _prefer(
        self,
        inventory: TpuHostInventory,
        available: list[str],
        must_include: list[str],
        size: int,
    ) -> list[str]:
        try:
            avail_idx = {inventory.chip_by_k8s_id(d).index for d in available}
            must_idx = {inventory.chip_by_k8s_id(d).index for d in must_include}
        except KeyError as e:
            log.warning("GetPreferredAllocation names unknown device %s", e)
            return sorted(available)[:size]
        by_index = {c.index: c for c in inventory.chips}
        sub = select_contiguous(
            size,
            avail_idx | must_idx,
            inventory.host_bounds,
            must_include=must_idx,
        )
        if sub is not None:
            return [
                by_index[i].k8s_id
                for i in sorted(sub.chip_indices(inventory.host_bounds))
            ]
        # No contiguous block containing the musts: fill musts first, then
        # lowest available indices (deterministic, NUMA-dense-ish).
        chosen = sorted(must_idx) + sorted(avail_idx - must_idx)
        return [by_index[i].k8s_id for i in chosen[:size]]

    # ---------------------------------------------------------- RPC: allocate

    def Allocate(self, request, context):
        _, inventory, health = self._snapshot()
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            ids = list(creq.devicesIDs)
            try:
                chips = [inventory.chip_by_k8s_id(d) for d in ids]
            except KeyError as e:
                context.abort(
                    grpc.StatusCode.NOT_FOUND, f"unknown device id {e.args[0]!r}"
                )
            unhealthy = [c.k8s_id for c in chips if not health.get(c.k8s_id)]
            if unhealthy:
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"device(s) {unhealthy} are Unhealthy",
                )
            resp.container_responses.append(self._allocate_one(inventory, chips))
            log.info("allocated %s", ids)
        return resp

    def _allocate_one(
        self, inventory: TpuHostInventory, chips: list[TpuChip]
    ) -> pb.ContainerAllocateResponse:
        car = pb.ContainerAllocateResponse()
        # Exactly the requested chips' device nodes — never the whole devfs.
        for chip in sorted(chips, key=lambda c: c.index):
            car.devices.add(
                container_path=chip.device_path,
                host_path=chip.device_path,
                permissions="rw",
            )
        sub = self._sub_mesh_of(inventory, chips)
        if sub is None and 1 < len(chips) < inventory.chip_count:
            log.warning(
                "allocation %s is not ICI-contiguous; claiming a chain "
                "(did the kubelet ignore GetPreferredAllocation?)",
                [c.k8s_id for c in chips],
            )
        for key, value in allocation_envs(inventory, chips, sub).items():
            car.envs[key] = value
        for key, value in allocation_annotations(chips).items():
            car.annotations[key] = value
        return car

    @staticmethod
    def _sub_mesh_of(
        inventory: TpuHostInventory, chips: list[TpuChip]
    ) -> SubMesh | None:
        indices = {c.index for c in chips}
        sub = select_contiguous(len(indices), indices, inventory.host_bounds)
        if sub is not None and set(sub.chip_indices(inventory.host_bounds)) == indices:
            return sub
        return None
