"""The google.com/tpu DevicePlugin gRPC service.

TPU-native re-design of the reference's `Plugin` (reference main.go:38-159),
fixing its known defects rather than reproducing them:

- `ListAndWatch` REBUILDS the full device list on every update (the reference
  appends to the previous slice, growing duplicates each heartbeat —
  main.go:126-132) and re-runs discovery on each poll, so hot-(un)plug is
  reflected (the reference counts once at stream start — main.go:105).
- Health is per-chip (health.py) instead of one node-global /dev/kfd open
  flipping everything (main.go:83-91,122).
- `Allocate` HONORS the requested device IDs, mounting exactly those
  /dev/accel* nodes and injecting mesh/topology env (the reference ignores the
  IDs and grants /dev/kfd + all of /dev/dri with no env — main.go:139-159).
- `GetPreferredAllocation` steers the kubelet toward ICI-contiguous sub-meshes
  (no reference analogue; the topology-data-but-no-code gap of SURVEY.md §2.4).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

import grpc

from ..kubelet import constants
from ..kubelet.api import pb
from ..utils import failpoints, tracing
from ..utils.anomaly import AnomalyMonitor
from ..utils.flight import FlightRecorder
from ..utils.metrics import MetricsRegistry
from ..utils.spans import SpanRecorder
from .discovery import TpuChip, TpuHostInventory
from .envs import allocation_annotations, allocation_envs
from .health import ChipHealthChecker
from .topology import SubMesh, select_contiguous

log = logging.getLogger(__name__)

RESOURCE_NAMESPACE = "google.com"
RESOURCE_NAME = "tpu"
RESOURCE = f"{RESOURCE_NAMESPACE}/{RESOURCE_NAME}"


def _chip_index_key(device_id: str) -> tuple[int, str]:
    """Numeric-aware sort key: ``tpu-2`` orders before ``tpu-10``.

    Lexicographic sort would scatter the fallback pick across the mesh on
    hosts with >9 chips (the 16-chip bounds entry exists in topology.py).
    """
    _, _, tail = device_id.rpartition("-")
    return (int(tail), device_id) if tail.isdigit() else (1 << 30, device_id)

# Process-wide registry: the daemon has exactly one plugin+manager, and a
# single registry keeps the /metrics endpoint wiring trivial.  Tests that need
# isolation construct their own MetricsRegistry and pass it in.
DEFAULT_REGISTRY = MetricsRegistry()

_default_metrics = None
_default_metrics_lock = threading.Lock()


def default_plugin_metrics() -> "PluginMetrics":
    """The PluginMetrics bound to DEFAULT_REGISTRY, created once (metric names
    may only be registered once per registry, and main() may run more than
    once in one process — hermetic tests do)."""
    global _default_metrics
    with _default_metrics_lock:
        if _default_metrics is None:
            _default_metrics = PluginMetrics(DEFAULT_REGISTRY)
        return _default_metrics


class PluginMetrics:
    """The plugin's instrumentation, named in Prometheus conventions.

    Beyond-reference observability (SURVEY.md §5.5 records the reference has
    none); every load-bearing event in the serve/stream/allocate paths gets a
    series here.
    """

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.chips = registry.gauge(
            "tpu_plugin_chips", "Discovered TPU chips by health state", ["state"]
        )
        self.device_updates = registry.counter(
            "tpu_plugin_device_updates_total",
            "State versions published to ListAndWatch streams",
        )
        self.health_transitions = registry.counter(
            "tpu_plugin_health_transitions_total",
            "Per-chip Healthy<->Unhealthy flips observed by polling",
            ["direction"],
        )
        self.streams = registry.gauge(
            "tpu_plugin_listandwatch_streams", "Open ListAndWatch streams"
        )
        self.allocations = registry.counter(
            "tpu_plugin_allocations_total",
            "Container allocation requests by outcome",
            ["outcome"],
        )
        self.allocated_chips = registry.counter(
            "tpu_plugin_allocated_chips_total", "Chips handed out by Allocate"
        )
        self.allocation_latency = registry.summary(
            "tpu_plugin_allocation_latency_seconds",
            "Wall time of Allocate RPCs (BASELINE.json secondary metric)",
        )
        self.allocate_seconds = registry.histogram(
            "tpu_plugin_allocate_seconds",
            "Wall time of Allocate RPCs (histogram: the p99 < 50 ms "
            "budget of docs/operations.md needs quantiles, which the "
            "older summary series cannot provide)",
            buckets=(
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0,
            ),
        )
        self.device_health = registry.gauge(
            "tpu_plugin_device_health",
            "Per-chip health (1 Healthy, 0 Unhealthy) as streamed to the "
            "kubelet; series are removed when a chip is unplugged",
            ["device"],
        )
        self.health_sweep_seconds = registry.histogram(
            "tpu_plugin_health_sweep_seconds",
            "Wall time of one full-inventory health sweep (the per-pulse "
            "hot path; native-prober sweeps are one FFI crossing)",
        )
        self.poll_failures = registry.counter(
            "tpu_plugin_poll_failures_total",
            "Heartbeat discovery/health polls that raised (the daemon "
            "keeps serving the last good snapshot)",
        )
        self.preferred_allocations = registry.counter(
            "tpu_plugin_preferred_allocations_total",
            "GetPreferredAllocation container requests by result",
            ["result"],
        )
        self.registrations = registry.counter(
            "tpu_plugin_registrations_total", "Successful kubelet registrations"
        )
        self.kubelet_restarts = registry.counter(
            "tpu_plugin_kubelet_restarts_total",
            "kubelet.sock recreations observed by the watcher",
        )
        self.incidents = registry.counter(
            "tpu_plugin_incidents_total",
            "Anomaly incidents emitted by the daemon-side monitor "
            "(utils/anomaly.py: Allocate latency, health-sweep duration); "
            "records served at the MetricsServer's /debug/incidents",
            ["metric"],
        )
        # Idle-chip self-test sweep (plugin/selftest.py, --selftest-*):
        # active correctness probes on chips the allocation ledger shows
        # idle.  Verdict is a closed set (pass/fail/skip_busy/error).
        self.selftests = registry.counter(
            "tpu_chip_selftest_total",
            "Idle-chip self-test probes per chip and verdict (pass: "
            "matmul checksum bit-exact; fail: diverged — "
            "fail_threshold consecutive fires selftest.fail and "
            "quarantines via the health override file; skip_busy: "
            "ledger shows the chip allocated, never probed; error: "
            "probe machinery raised)",
            ["device", "verdict"],
        )
        self.selftest_seconds = registry.histogram(
            "tpu_chip_selftest_seconds",
            "Wall time of one idle-chip self-test probe (seeded int64 "
            "matmul + crc32)",
            buckets=(
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 1.0,
            ),
        )
        self.selftest_quarantined = registry.gauge(
            "tpu_chip_selftest_quarantined",
            "1 while the chip sits quarantined by a failed self-test "
            "(health override file written; operator removes it to "
            "recover — docs/operations.md triage table)",
            ["device"],
        )
        # --- pod attribution (plugin/attribution.py).  Cardinality is
        # bounded by the host's chip count (<= 16): at most one
        # owner-info series per chip and one tpu_pod_chips series per
        # chip-holding pod; series are removed the poll after their pod
        # goes away (the unplug pattern of device_health).
        self.chip_owner = registry.gauge(
            "tpu_chip_owner_info",
            "Chip ownership joined from the kubelet PodResources API: "
            "constant 1 per (device, namespace, pod, container); series "
            "removed when the pod releases the chip",
            ["device", "namespace", "pod", "container"],
        )
        self.pod_chips = registry.gauge(
            "tpu_pod_chips",
            "Chips the kubelet currently attributes to each pod; series "
            "removed when the pod goes away",
            ["namespace", "pod"],
        )
        self.attribution_attributed = registry.gauge(
            "tpu_attribution_attributed_chips",
            "Chips the kubelet currently attributes to pods (attributed "
            "< allocatable is normal slack; attributed > allocatable is "
            "drift territory)",
        )
        self.attribution_allocatable = registry.gauge(
            "tpu_attribution_allocatable_chips",
            "Allocatable devices reported by the kubelet's "
            "GetAllocatableResources for the plugin's resources",
        )
        self.podresources_up = registry.gauge(
            "tpu_podresources_up",
            "1 when the kubelet PodResources socket answered the last "
            "attribution poll; 0 when unconfigured, absent, or "
            "unresponsive (the daemon degrades gracefully either way)",
        )
        self.attribution_poll_seconds = registry.histogram(
            "tpu_attribution_poll_seconds",
            "Wall time of one PodResources attribution poll (List + "
            "periodic GetAllocatableResources + ownership diff + "
            "reconciliation audit); budget < 1 ms against a local socket",
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 1.0,
            ),
        )
        self.attribution_drift = registry.counter(
            "tpu_attribution_drift_total",
            "Allocation-reconciliation drift: kubelet attributes a chip "
            "the plugin never granted (kind=ungranted) or a granted chip "
            "the kubelet never surfaced within the confirmation grace "
            "(kind=unfulfilled)",
            ["kind"],
        )


class TpuDevicePlugin:
    """DevicePlugin servicer for one node's TPU chips.

    Thread-safe: the manager's heartbeat thread calls :meth:`poll_once` while
    kubelet RPCs arrive on gRPC worker threads; every ListAndWatch stream
    waits on one condition variable and re-sends a full snapshot whenever the
    state version advances.
    """

    def __init__(
        self,
        discover: Callable[[], TpuHostInventory],
        health_checker: ChipHealthChecker,
        metrics: PluginMetrics | None = None,
        flight: FlightRecorder | None = None,
        anomaly: AnomalyMonitor | None = None,
        spans: SpanRecorder | None = None,
        ledger=None,
    ):
        self._discover = discover
        self._health_checker = health_checker
        self.metrics = metrics if metrics is not None else PluginMetrics(MetricsRegistry())
        # Allocation ledger (plugin/attribution.py AllocationLedger):
        # every granted device ID lands here so the attribution poller
        # can diff kubelet truth against what we actually handed out.
        # Optional like the forensics hooks — bare test constructions
        # stay ledger-free.
        self.ledger = ledger
        # Forensics (cli.py wires shared instances; all optional here so
        # bare test constructions stay zero-cost): a flight-recorder
        # black box of daemon lifecycle events, an anomaly monitor over
        # Allocate latency, and a daemon span ring fed by timed_rpc.
        self.flight = flight
        self.anomaly = anomaly
        self.spans = spans
        if anomaly is not None:
            anomaly.configure(
                "plugin.allocate_seconds", warmup=20, z_threshold=6.0,
                sustain=2,
            )
        # Route the kubelet-facing RPC surface through timed_rpc (one
        # tracing story, two entry points): every Allocate /
        # GetPreferredAllocation lands in the daemon span ring with the
        # DAEMON_TRACE id.  Instance-level wrap because the recorder is
        # per-instance; the metrics histograms inside Allocate are
        # untouched (observe= stays for callers without a histogram).
        if spans is not None:
            self.Allocate = tracing.timed_rpc(
                self.Allocate, spans=lambda: self.spans, threshold_ms=50.0
            )
            self.GetPreferredAllocation = tracing.timed_rpc(
                self.GetPreferredAllocation, spans=lambda: self.spans
            )
        self._cond = threading.Condition()
        self._version = 0
        self._epoch = 0  # bumped by interrupt_streams(); streams die on change
        self._inventory: TpuHostInventory | None = None
        self._health: dict[str, bool] = {}  # k8s_id -> healthy
        self.poll_once()

    def interrupt_streams(self) -> None:
        """End every open ListAndWatch stream promptly (server shutdown /
        restart); streams opened afterwards are unaffected."""
        with self._cond:
            self._epoch += 1
            self._cond.notify_all()

    # ------------------------------------------------------------------ state

    def poll_once(self) -> bool:
        """Re-discover chips and re-check health; returns True if anything
        changed (and wakes every ListAndWatch stream)."""
        inventory = self._discover()
        health = self._health_checker.check_many(inventory.chips)
        with self._cond:
            changed = (
                self._inventory is None
                or health != self._health
                or [c.k8s_id for c in inventory.chips]
                != [c.k8s_id for c in self._inventory.chips]
            )
            for k8s_id, healthy in health.items():
                was = self._health.get(k8s_id)
                if was is not None and was != healthy:
                    self.metrics.health_transitions.inc(
                        direction="to_unhealthy" if was else "to_healthy"
                    )
                    if self.flight is not None:
                        self.flight.record(
                            "health.transition",
                            device=k8s_id,
                            to="Unhealthy" if was else "Healthy",
                        )
            # Per-device health series track the streamed device list
            # exactly: an unplugged chip's series is removed, not frozen
            # at its last value (a flat 1 for a missing chip would read
            # as healthy on a dashboard).  Inventory membership changes
            # are also flight events BY DEVICE — /dev/accel* is
            # authoritative for existence (discovery.py), so a yanked
            # chip leaves the inventory without ever probing Unhealthy,
            # and health.transition alone would never name it.
            for k8s_id in self._health.keys() - health.keys():
                self.metrics.device_health.remove(device=k8s_id)
                if self.flight is not None:
                    self.flight.record("device.unplug", device=k8s_id)
            if self._inventory is not None and self.flight is not None:
                for k8s_id in health.keys() - self._health.keys():
                    self.flight.record("device.plug", device=k8s_id)
            for k8s_id, healthy in health.items():
                self.metrics.device_health.set(
                    1.0 if healthy else 0.0, device=k8s_id
                )
            self._inventory = inventory
            self._health = health
            if changed:
                self._version += 1
                self._cond.notify_all()
            version = self._version
        self.metrics.chips.set(sum(health.values()), state="healthy")
        self.metrics.chips.set(len(health) - sum(health.values()), state="unhealthy")
        if changed:
            self.metrics.device_updates.inc()
            if self.flight is not None:
                self.flight.record(
                    "listandwatch.update",
                    version=version,
                    chips=len(health),
                    healthy=sum(health.values()),
                )
            log.info(
                "device state v%d: %s",
                version,
                {k: ("Healthy" if v else "Unhealthy") for k, v in health.items()},
            )
        return changed

    def _snapshot(self) -> tuple[int, TpuHostInventory, dict[str, bool]]:
        with self._cond:
            assert self._inventory is not None
            return self._version, self._inventory, dict(self._health)

    @property
    def inventory(self) -> TpuHostInventory:
        """Latest discovered inventory (for CLI/observability consumers)."""
        return self._snapshot()[1]

    def debug_state(self) -> dict:
        """JSON-safe daemon snapshot for the MetricsServer's
        ``/debug/devices`` endpoint: the device list as the kubelet sees
        it — ids, device paths, NUMA placement, topology coordinates,
        health — plus the state version, so an operator can confirm what
        a node is ADVERTISING without gRPC-poking the kubelet socket
        (the daemon-side analogue of the engine's /debug/state)."""
        version, inventory, health = self._snapshot()
        return {
            "resource": RESOURCE,
            "state_version": version,
            "chip_count": inventory.chip_count,
            "accelerator_type": inventory.accelerator_type,
            "host_bounds": inventory.host_bounds,
            "chips": [
                {
                    "id": chip.k8s_id,
                    "index": chip.index,
                    "device_path": chip.device_path,
                    "numa_node": chip.numa_node,
                    "healthy": bool(health.get(chip.k8s_id)),
                }
                for chip in inventory.chips
            ],
        }

    def device_info(self) -> dict[str, dict]:
        """Per-chip discovery/topology/health join keyed by k8s device ID —
        what the attribution poller merges under each pod's devices in
        ``GET /debug/pods`` (chip index, ICI coords, NUMA, health)."""
        _, inventory, health = self._snapshot()
        return {
            chip.k8s_id: {
                "index": chip.index,
                "device_path": chip.device_path,
                "numa_node": chip.numa_node,
                "coords": list(inventory.coords_of(chip)),
                "healthy": bool(health.get(chip.k8s_id)),
            }
            for chip in inventory.chips
        }

    def _device_list(self, inventory: TpuHostInventory, health: dict[str, bool]):
        devices = []
        for chip in inventory.chips:
            dev = pb.Device(
                ID=chip.k8s_id,
                health=constants.HEALTHY if health.get(chip.k8s_id) else constants.UNHEALTHY,
            )
            if chip.numa_node is not None and chip.numa_node >= 0:
                dev.topology.nodes.add(ID=chip.numa_node)
            devices.append(dev)
        return devices

    # ------------------------------------------------------------- RPC: admin

    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(
            pre_start_required=False,
            get_preferred_allocation_available=True,
        )

    def PreStartContainer(self, request, context):
        return pb.PreStartContainerResponse()

    # ------------------------------------------------------------ RPC: stream

    def ListAndWatch(self, request, context):
        try:
            # Chaos seam (docs/chaos.md): error refuses the stream (the
            # kubelet's run loop re-dials), delay stalls its opening.
            failpoints.fire("plugin.listandwatch", op="open")
        except failpoints.FailpointError as e:
            if self.flight is not None:
                self.flight.record(
                    "listandwatch.stream", op="failpoint", error=str(e)
                )
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        with self._cond:
            epoch = self._epoch
        version, inventory, health = self._snapshot()
        log.info("ListAndWatch stream opened (v%d, %d chips)", version, inventory.chip_count)
        self.metrics.streams.inc()
        if self.flight is not None:
            self.flight.record(
                "listandwatch.stream", op="open", version=version
            )
        try:
            yield pb.ListAndWatchResponse(devices=self._device_list(inventory, health))
            while True:
                with self._cond:
                    # Wake on state change or interrupt; time out periodically to
                    # notice a disconnected kubelet and end the stream cleanly.
                    while self._version == version and self._epoch == epoch:
                        if not self._cond.wait(timeout=5.0):
                            if not context.is_active():
                                log.info("ListAndWatch stream closed by peer")
                                return
                    if self._epoch != epoch:
                        log.info("ListAndWatch stream interrupted (server stopping)")
                        return
                    version = self._version
                    inventory, health = self._inventory, dict(self._health)
                if not context.is_active():
                    return
                try:
                    # Per-update chaos seam: error kills the live stream
                    # mid-flight (the kubelet must notice and re-dial);
                    # delay stalls the device update — detection-latency
                    # injection for the scenario suite.
                    failpoints.fire(
                        "plugin.listandwatch", op="update", version=version
                    )
                except failpoints.FailpointError as e:
                    log.warning("ListAndWatch stream killed by failpoint: %s", e)
                    if self.flight is not None:
                        self.flight.record(
                            "listandwatch.stream", op="failpoint", error=str(e)
                        )
                    return
                yield pb.ListAndWatchResponse(devices=self._device_list(inventory, health))
        finally:
            self.metrics.streams.dec()
            if self.flight is not None:
                self.flight.record("listandwatch.stream", op="close")

    # --------------------------------------------------- RPC: preferred alloc

    def GetPreferredAllocation(self, request, context):
        _, inventory, _ = self._snapshot()
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            preferred = self._prefer(
                inventory,
                available=list(creq.available_deviceIDs),
                must_include=list(creq.must_include_deviceIDs),
                size=creq.allocation_size,
            )
            resp.container_responses.add(deviceIDs=preferred)
        return resp

    def _record_preference(self, contiguous: bool) -> None:
        self.metrics.preferred_allocations.inc(
            result="contiguous" if contiguous else "fragmented"
        )

    def _prefer(
        self,
        inventory: TpuHostInventory,
        available: list[str],
        must_include: list[str],
        size: int,
    ) -> list[str]:
        try:
            avail_idx = {inventory.chip_by_k8s_id(d).index for d in available}
            must_idx = {inventory.chip_by_k8s_id(d).index for d in must_include}
        except KeyError as e:
            log.warning("GetPreferredAllocation names unknown device %s", e)
            self.metrics.preferred_allocations.inc(result="unknown_device")
            return sorted(available, key=_chip_index_key)[:size]
        by_index = {c.index: c for c in inventory.chips}
        sub = select_contiguous(
            size,
            avail_idx | must_idx,
            inventory.host_bounds,
            must_include=must_idx,
        )
        if sub is not None:
            self._record_preference(contiguous=True)
            return [
                by_index[i].k8s_id
                for i in sorted(sub.chip_indices(inventory.host_bounds))
            ]
        # No contiguous block containing the musts: fill musts first, then
        # lowest available indices (deterministic, NUMA-dense-ish).
        self._record_preference(contiguous=False)
        chosen = sorted(must_idx) + sorted(avail_idx - must_idx)
        return [by_index[i].k8s_id for i in chosen[:size]]

    # ---------------------------------------------------------- RPC: allocate

    def Allocate(self, request, context):
        t0 = time.monotonic()
        with self.metrics.allocation_latency.time(), \
                self.metrics.allocate_seconds.time():
            try:
                # Chaos seam (docs/chaos.md): error aborts the RPC
                # UNAVAILABLE (the kubelet fails the pod's admission and
                # retries); delay/hang stall INSIDE the latency
                # histograms, so the injected slowness feeds the same
                # Allocate-latency anomaly baseline real slowness would.
                failpoints.fire(
                    "plugin.allocate",
                    containers=len(request.container_requests),
                )
            except failpoints.FailpointError as e:
                self.metrics.allocations.inc(outcome="failpoint")
                if self.flight is not None:
                    self.flight.record(
                        "allocate", outcome="failpoint", error=str(e)
                    )
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            _, inventory, health = self._snapshot()
            resp = pb.AllocateResponse()
            granted_chips = 0
            granted_ids: list[str] = []
            for creq in request.container_requests:
                ids = list(creq.devicesIDs)
                try:
                    chips = [inventory.chip_by_k8s_id(d) for d in ids]
                except KeyError as e:
                    self.metrics.allocations.inc(outcome="unknown_device")
                    if self.flight is not None:
                        self.flight.record(
                            "allocate", ids=ids, outcome="unknown_device"
                        )
                    context.abort(
                        grpc.StatusCode.NOT_FOUND, f"unknown device id {e.args[0]!r}"
                    )
                unhealthy = [c.k8s_id for c in chips if not health.get(c.k8s_id)]
                if unhealthy:
                    self.metrics.allocations.inc(outcome="unhealthy_device")
                    if self.flight is not None:
                        self.flight.record(
                            "allocate",
                            ids=ids,
                            outcome="unhealthy_device",
                            unhealthy=unhealthy,
                        )
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION,
                        f"device(s) {unhealthy} are Unhealthy",
                    )
                resp.container_responses.append(self._allocate_one(inventory, chips))
                granted_chips += len(chips)
                granted_ids.extend(ids)
                log.info("allocated %s", ids)
            # Success counters only once the WHOLE response is built: a later
            # container's abort discards the entire AllocateResponse, and the
            # metrics must not claim chips were handed out.  Same rule for
            # the reconciliation ledger: an aborted Allocate granted nothing.
            self.metrics.allocations.inc(
                len(request.container_requests), outcome="ok"
            )
            self.metrics.allocated_chips.inc(granted_chips)
            if self.ledger is not None:
                self.ledger.grant(granted_ids)
        dt = time.monotonic() - t0
        if self.flight is not None:
            self.flight.record(
                "allocate",
                outcome="ok",
                containers=len(request.container_requests),
                chips=granted_chips,
                ms=round(dt * 1e3, 3),
            )
        if self.anomaly is not None:
            # Sustained Allocate-latency blowups (wedged devfs, lock
            # contention) become incident records with the lead-up
            # events attached — the pod-startup-path SLO guard.
            self.anomaly.observe("plugin.allocate_seconds", dt)
        return resp

    def _allocate_one(
        self, inventory: TpuHostInventory, chips: list[TpuChip]
    ) -> pb.ContainerAllocateResponse:
        car = pb.ContainerAllocateResponse()
        # Exactly the requested chips' device nodes — never the whole devfs.
        for chip in sorted(chips, key=lambda c: c.index):
            car.devices.add(
                container_path=chip.device_path,
                host_path=chip.device_path,
                permissions="rw",
            )
        sub = self._sub_mesh_of(inventory, chips)
        if sub is None and 1 < len(chips) < inventory.chip_count:
            log.warning(
                "allocation %s is not ICI-contiguous; claiming a chain "
                "(did the kubelet ignore GetPreferredAllocation?)",
                [c.k8s_id for c in chips],
            )
        for key, value in allocation_envs(inventory, chips, sub).items():
            car.envs[key] = value
        for key, value in allocation_annotations(chips).items():
            car.annotations[key] = value
        return car

    @staticmethod
    def _sub_mesh_of(
        inventory: TpuHostInventory, chips: list[TpuChip]
    ) -> SubMesh | None:
        indices = {c.index for c in chips}
        sub = select_contiguous(len(indices), indices, inventory.host_bounds)
        if sub is not None and set(sub.chip_indices(inventory.host_bounds)) == indices:
            return sub
        return None
