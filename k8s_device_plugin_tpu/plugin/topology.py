"""ICI mesh topology model for the chips on one host.

The reference collected interconnect topology in its sysfs fixture but never
used it (SURVEY.md §2.4: `countGPUDev` at reference main.go:50-81 reads only
`simd_count`).  For TPUs the host-local ICI mesh is load-bearing: a multi-chip
allocation must be mesh-contiguous or the workload's collectives fall off ICI.
This module owns:

- the (x, y, z) bounds of the chips on one host for each supported host shape,
- chip-index <-> mesh-coordinate mapping (row-major, x fastest),
- contiguous sub-mesh selection for `Allocate` requests smaller than a host.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Iterable, Sequence

# Host-local chip-mesh bounds by chip count.  TPU hosts expose 1, 4, or 8
# chips; 4-chip hosts are a 2x2 ICI square (e.g. v4 / v5p / one v5e "sub-host"
# group), 8-chip hosts a 2x4 (v5e/v6e full host).  An unrecognized count is
# treated as a 1-D chain as the least-structured assumption available (note a
# chain still asserts links between consecutive chips).
CHIPS_PER_HOST_BOUNDS: dict[int, tuple[int, int, int]] = {
    1: (1, 1, 1),
    2: (2, 1, 1),
    4: (2, 2, 1),
    8: (2, 4, 1),
    16: (4, 4, 1),
}


def host_bounds_for_count(n_chips: int) -> tuple[int, int, int]:
    """Bounds of the host-local chip mesh for ``n_chips`` chips."""
    return CHIPS_PER_HOST_BOUNDS.get(n_chips, (n_chips, 1, 1))


def chip_coords(index: int, bounds: tuple[int, int, int]) -> tuple[int, int, int]:
    """Mesh coordinates of host-local chip ``index``; x varies fastest."""
    bx, by, _bz = bounds
    x = index % bx
    y = (index // bx) % by
    z = index // (bx * by)
    return (x, y, z)


def chip_index(coords: tuple[int, int, int], bounds: tuple[int, int, int]) -> int:
    bx, by, _bz = bounds
    x, y, z = coords
    return x + bx * (y + by * z)


@dataclass(frozen=True)
class SubMesh:
    """A contiguous axis-aligned block of the host chip mesh."""

    origin: tuple[int, int, int]
    bounds: tuple[int, int, int]  # extent along (x, y, z)

    def chip_indices(self, host_bounds: tuple[int, int, int]) -> tuple[int, ...]:
        ox, oy, oz = self.origin
        sx, sy, sz = self.bounds
        return tuple(
            sorted(
                chip_index((ox + dx, oy + dy, oz + dz), host_bounds)
                for dz in range(sz)
                for dy in range(sy)
                for dx in range(sx)
            )
        )


def _block_shapes(count: int, host_bounds: tuple[int, int, int]) -> list[tuple[int, int, int]]:
    """All (sx, sy, sz) factorizations of ``count`` that fit in the host mesh,
    most compact (closest to a cube/square) first — compact blocks have the
    shortest ICI diameter, which is what collective latency tracks."""
    bx, by, bz = host_bounds
    shapes = set()
    for sx in range(1, min(count, bx) + 1):
        if count % sx:
            continue
        rest = count // sx
        for sy in range(1, min(rest, by) + 1):
            if rest % sy:
                continue
            sz = rest // sy
            if sz <= bz:
                shapes.add((sx, sy, sz))
    # Compactness: minimize mesh diameter (sum of extents), tie-break on
    # larger x-extent for deterministic output.
    return sorted(shapes, key=lambda s: (s[0] + s[1] + s[2], -s[0]))


def select_contiguous(
    count: int,
    available: Iterable[int],
    host_bounds: tuple[int, int, int],
    must_include: Iterable[int] = (),
) -> SubMesh | None:
    """Pick a mesh-contiguous block of ``count`` chips from ``available``.

    Returns the most compact axis-aligned sub-mesh whose chips are all
    available and which contains every chip in ``must_include``, or None if no
    such block exists (the caller may then fall back to an arbitrary subset).
    """
    avail = frozenset(available)
    must = frozenset(must_include)
    if count <= 0 or len(avail | must) < count or len(must) > count:
        return None
    bx, by, bz = host_bounds
    for shape in _block_shapes(count, host_bounds):
        sx, sy, sz = shape
        for oz, oy, ox in itertools.product(
            range(bz - sz + 1), range(by - sy + 1), range(bx - sx + 1)
        ):
            sub = SubMesh(origin=(ox, oy, oz), bounds=shape)
            indices = set(sub.chip_indices(host_bounds))
            if indices <= (avail | must) and must <= indices:
                return sub
    return None


def bounds_str(bounds: Sequence[int]) -> str:
    """Render bounds the way libtpu env vars expect: "x,y,z"."""
    return ",".join(str(b) for b in bounds)
