"""Multi-resource lifecycle: host N device-plugin resources in one process.

The reference's DPM is a *generic* manager: `ListerInterface.Discover`
streams lists of resource last-names over a channel, and the manager diffs
each list against the running set, starting a plugin server for every new
name and stopping the server of every vanished one (reference
dpm/lister.go:11-26 — GetResourceNamespace/Discover/NewPlugin;
dpm/manager.go:96-136 — handleNewPlugins start/stop set-diff).  Round 1
hardcoded a single `google.com/tpu` plugin; this module supplies the general
contract so the lifecycle layer can host e.g. `google.com/tpu` plus a future
`google.com/tpu-slice` with dynamic add/remove.

Differences from the reference, on purpose:

- ONE kubelet-socket watch for the whole process, fanned into every
  per-resource manager (the reference also holds one fsnotify watch;
  per-resource watches would multiply inotify descriptors for nothing).
- Discovery pushes via a callback instead of a channel — the Python-native
  shape of the same contract; the publisher thread is owned by the manager
  exactly like dpm runs Discover in a goroutine (dpm/manager.go:63).
- Start/stop on diff reuses PluginManager (idempotent start, registration
  rollback, retry w/ backoff) rather than reimplementing it, so single- and
  multi-resource deployments share one battle path.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Iterable, Protocol

from ..kubelet import constants
from .manager import PluginManager
from .server import TpuDevicePlugin

log = logging.getLogger(__name__)

PublishFn = Callable[[Iterable[str]], None]


class ResourceLister(Protocol):
    """≙ dpm ListerInterface (reference dpm/lister.go:11-26).

    `namespace` is the resource-name prefix ("google.com" ⇒ resources
    "google.com/<name>").  `discover` runs on a manager-owned thread and
    calls `publish` with the full current name list whenever it changes
    (publishing the same list twice is harmless); it must return promptly
    once `stop` is set.  `new_plugin` builds the servicer for one name.
    """

    namespace: str

    def discover(self, publish: PublishFn, stop: threading.Event) -> None: ...

    def new_plugin(self, name: str) -> TpuDevicePlugin: ...


class StaticLister:
    """Simplest lister: one fixed name list, published once (≙ the reference
    main.go probe goroutine pushing ["gpu"] a single time, main.go:211-217)."""

    def __init__(
        self,
        names: Iterable[str],
        new_plugin: Callable[[str], TpuDevicePlugin],
        namespace: str = "google.com",
    ):
        self.namespace = namespace
        self._names = list(names)
        self._new_plugin = new_plugin

    def discover(self, publish: PublishFn, stop: threading.Event) -> None:
        publish(self._names)

    def new_plugin(self, name: str) -> TpuDevicePlugin:
        return self._new_plugin(name)


class MultiResourceManager:
    """Owns the discover thread, the shared kubelet watch, and one
    PluginManager per live resource name (≙ dpm Manager, dpm/manager.go)."""

    def __init__(
        self,
        lister: ResourceLister,
        plugin_dir: str = constants.DEVICE_PLUGIN_PATH,
        pulse: float = 0.0,
        register_retries: int = 3,
        register_retry_delay: float = 3.0,
        watch_poll_interval: float = 1.0,
    ):
        self.lister = lister
        self.plugin_dir = plugin_dir
        self.pulse = pulse
        self._register_retries = register_retries
        self._register_retry_delay = register_retry_delay
        self._watch_poll_interval = watch_poll_interval

        self._lock = threading.Lock()  # guards _managers/_starting/_wanted
        self._managers: dict[str, PluginManager] = {}
        self._starting: set[str] = set()  # reserved while a start is in flight
        self._wanted: set[str] = set()  # the most recently published list
        self._stop = threading.Event()
        self._watcher = None
        self._discover_thread: threading.Thread | None = None
        self._retry_thread: threading.Thread | None = None
        self._discover_failed = False

    # ----------------------------------------------------------------- naming

    def resource_name(self, name: str) -> str:
        return f"{self.lister.namespace}/{name}"

    def endpoint(self, name: str) -> str:
        # ≙ dpm/plugin.go:51-58 socket naming: <namespace>_<name>.
        return f"{self.lister.namespace}_{name}.sock"

    # ------------------------------------------------------------- lifecycle

    def run(self) -> None:
        self.start()
        try:
            self._stop.wait()
        finally:
            self.stop_all()

    def start(self) -> None:
        from .watcher import KubeletSocketWatcher

        self._watcher = KubeletSocketWatcher(
            self.plugin_dir,
            constants.KUBELET_SOCKET_NAME,
            on_create=self._on_kubelet_create,
            on_remove=self._on_kubelet_remove,
            poll_interval=self._watch_poll_interval,
        )
        self._watcher.start()
        if not self._watcher.ready.wait(timeout=10):
            log.warning("socket watcher failed to arm within 10s")
        self._discover_thread = threading.Thread(
            target=self._discover_loop, name="resource-discover", daemon=True
        )
        self._discover_thread.start()
        self._retry_thread = threading.Thread(
            target=self._retry_loop, name="resource-retry", daemon=True
        )
        self._retry_thread.start()

    def shutdown(self) -> None:
        self._stop.set()

    def alive(self) -> bool:
        """Same liveness contract as PluginManager: a dead recovery path IS
        death.  A discover thread that *returned* is fine (StaticLister
        publishes once and exits); one that *raised* means add/remove
        reconciliation is gone for good, so /healthz must go red."""
        if self._stop.is_set() or self._discover_failed:
            return False
        if self._retry_thread is not None and not self._retry_thread.is_alive():
            return False  # failed starts would never be retried again
        return self._watcher is not None and self._watcher.is_alive()

    def stop_all(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.stop()
            self._watcher.join(timeout=5)
            self._watcher = None
        if self._discover_thread is not None:
            self._discover_thread.join(timeout=5)
            self._discover_thread = None
        if self._retry_thread is not None:
            self._retry_thread.join(timeout=5)
            self._retry_thread = None
        with self._lock:
            managers, self._managers = dict(self._managers), {}
        for name, mgr in managers.items():
            log.info("stopping plugin for %s", self.resource_name(name))
            mgr.stop_all()

    # ------------------------------------------------------------- discovery

    def _discover_loop(self) -> None:
        try:
            self.lister.discover(self.publish, self._stop)
        except Exception:
            self._discover_failed = True
            log.exception("resource discover loop died")

    def _retry_loop(self) -> None:
        """Timer-driven recovery for wanted-but-not-running resources (their
        start failed — e.g. the kubelet rejected registration during a skewed
        upgrade).  The kubelet-create event retries too, but a kubelet that
        stays up emits no further events; like PluginManager's reconciler,
        recovery must not depend on one arriving."""
        period = max(self._register_retry_delay, 0.2) * 3
        while not self._stop.wait(period):
            # The kubelet-down case belongs to _on_kubelet_create (starting
            # servers against an absent socket just burns full registration
            # backoff cycles); the timer covers kubelet-up-but-rejecting.
            if not os.path.exists(os.path.join(self.plugin_dir, constants.KUBELET_SOCKET_NAME)):
                continue
            self._retry_missing("retry timer")

    def publish(self, names: Iterable[str]) -> None:
        """Reconcile the running plugin set against `names` (the full list,
        not a delta) — ≙ dpm handleNewPlugins (dpm/manager.go:96-136).

        Concurrency-safe against duplicate/overlapping publishes: a name is
        *reserved* in `_starting` under the lock before its (slow, lock-free)
        server start, so a second publisher can neither start a twin — whose
        `_start_server` would steal the live socket path — nor observe a
        half-started resource.  A start that completes after the name was
        un-wanted (or after shutdown) is rolled back, not committed.
        """
        wanted = set(names)
        with self._lock:
            self._wanted = set(wanted)
            if self._stop.is_set():
                return
            to_stop: dict[str, PluginManager] = {}
            to_start: list[str] = []
            for name in list(self._managers):
                if name not in wanted:
                    to_stop[name] = self._managers.pop(name)
            for name in sorted(wanted):
                if name not in self._managers and name not in self._starting:
                    self._starting.add(name)
                    to_start.append(name)
        for name, mgr in to_stop.items():
            log.info("resource %s vanished; stopping its plugin", self.resource_name(name))
            mgr.stop_all()
        self._start_names(to_start)

    def _retry_missing(self, why: str) -> None:
        """Start-only reconcile: begin every wanted-but-not-running resource.
        Retry paths must NEVER derive a stop-set from a snapshot of _wanted —
        a concurrent discover publish may have grown it, and stopping from
        the stale view would silently unregister the new resource until the
        next unrelated list change (listers only publish on change)."""
        with self._lock:
            if self._stop.is_set():
                return
            missing = sorted(self._wanted - set(self._managers) - self._starting)
            for name in missing:
                self._starting.add(name)
        if missing:
            log.info(
                "%s; retrying %s",
                why,
                [self.resource_name(n) for n in missing],
            )
            self._start_names(missing)

    def _start_names(self, to_start: list[str]) -> None:
        for name in to_start:
            try:
                mgr = PluginManager(
                    plugin=self.lister.new_plugin(name),
                    plugin_dir=self.plugin_dir,
                    endpoint=self.endpoint(name),
                    resource=self.resource_name(name),
                    pulse=self.pulse,
                    register_retries=self._register_retries,
                    register_retry_delay=self._register_retry_delay,
                    watch_kubelet=False,  # we fan the shared watch into it
                )
                mgr.start()
            except Exception:
                with self._lock:
                    self._starting.discard(name)
                # Not dropped forever: the name stays in _wanted; the
                # kubelet-create event and the retry timer both re-attempt
                # it (see _retry_missing).
                log.exception(
                    "failed to start plugin for %s (will retry)",
                    self.resource_name(name),
                )
                continue
            with self._lock:
                self._starting.discard(name)
                if self._stop.is_set() or name not in self._wanted:
                    undo = True  # raced with shutdown or a removing publish
                else:
                    self._managers[name] = mgr
                    undo = False
            if undo:
                mgr.stop_all()
        log.info(
            "resource set now: %s",
            sorted(self.resource_name(n) for n in self.resources()),
        )

    def resources(self) -> list[str]:
        with self._lock:
            return sorted(self._managers)

    def manager(self, name: str) -> PluginManager | None:
        with self._lock:
            return self._managers.get(name)

    # ------------------------------------------------------------- recovery

    def _snapshot(self) -> list[PluginManager]:
        with self._lock:
            return list(self._managers.values())

    def _on_kubelet_create(self) -> None:
        if self._stop.is_set():
            return
        for mgr in self._snapshot():
            mgr.handle_kubelet_create()
        # Wanted resources with no running manager (their start failed while
        # the kubelet was down) get another chance now that it's back —
        # without this they'd wait for the retry timer's next tick.
        self._retry_missing("kubelet is back")

    def _on_kubelet_remove(self) -> None:
        for mgr in self._snapshot():
            mgr.handle_kubelet_remove()
