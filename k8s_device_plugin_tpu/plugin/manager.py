"""Plugin lifecycle: serve, register, survive kubelet restarts.

A threaded re-expression of the reference's DPM framework (reference
dpm/manager.go + dpm/plugin.go), with its sharp edges filed off:

- server start is idempotent and mutex-guarded (≙ dpm/plugin.go:62-90) and
  retried 3×/3s (≙ dpm/manager.go:17-20,204-218),
- registration failure rolls the server back per the protocol's
  "terminate upon registration failure" contract (≙ dpm/plugin.go:83-87),
- kubelet.sock events are LEVEL-triggered, not edge-replayed: watcher
  events (≙ dpm/manager.go:73-84, via watcher.KubeletSocketWatcher) only
  kick a reconciler thread that compares the CURRENT socket identity
  (inode+ctime) against the identity we last registered with — socket
  present with a new identity ⇒ full restart + re-register, absent ⇒
  stop.  A kubelet flapping N times while we were busy costs ONE
  reconcile against its final state, not N replayed restart dances
  (the reference replays each fsnotify event),
- a heartbeat thread drives per-chip health/discovery polls (≙ the reference's
  ticker goroutine at main.go:201-209, minus its duplicate-append bug),
- no 10-second startup stall: the reference's readiness loop waited for a
  service count that could never be reached (dpm/plugin.go:114-120); grpc's
  server.start() needs no such poll.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent import futures

import grpc

from ..kubelet import constants
from ..kubelet.api import RegistrationStub, add_device_plugin_servicer, pb
from .server import RESOURCE, TpuDevicePlugin

log = logging.getLogger(__name__)

DEFAULT_ENDPOINT = "google.com_tpu.sock"


class PluginManager:
    """Owns the gRPC server, kubelet registration, and recovery threads for
    one resource (google.com/tpu)."""

    def __init__(
        self,
        plugin: TpuDevicePlugin,
        plugin_dir: str = constants.DEVICE_PLUGIN_PATH,
        endpoint: str = DEFAULT_ENDPOINT,
        resource: str = RESOURCE,
        pulse: float = 0.0,
        register_retries: int = 3,
        register_retry_delay: float = 3.0,
        watch_poll_interval: float = 1.0,
        watch_kubelet: bool = True,
    ):
        self.plugin = plugin
        self.plugin_dir = plugin_dir
        self.endpoint = endpoint
        self.resource = resource
        self.pulse = pulse
        self._register_retries = register_retries
        self._register_retry_delay = register_retry_delay
        self._watch_poll_interval = watch_poll_interval
        # False when a MultiResourceManager owns the (single, shared) kubelet
        # socket watch and fans events into us via handle_kubelet_* — one
        # inotify watch per process, not per resource (≙ the reference dpm
        # Manager owning fsnotify for all plugins, dpm/manager.go:53-84).
        self._watch_kubelet = watch_kubelet

        self._lock = threading.Lock()  # guards _server lifecycle
        self._server: grpc.Server | None = None
        self._stop = threading.Event()
        self._watcher = None
        self._heartbeat: threading.Thread | None = None
        self.registrations = 0  # observability: how many times we registered
        # Level-triggered recovery: watcher/fan-in events only set this
        # kick; the reconciler thread compares current socket identity to
        # _registered_key and acts on the DELTA (coalescing any number of
        # flaps into one reconcile).
        self._reconcile_kick = threading.Event()
        self._reconciler: threading.Thread | None = None
        self._registered_key: tuple | None = None
        self._counted_key: tuple | None = None  # last incarnation metered

    # ----------------------------------------------------------------- paths

    @property
    def socket_path(self) -> str:
        return os.path.join(self.plugin_dir, self.endpoint)

    @property
    def kubelet_socket(self) -> str:
        return os.path.join(self.plugin_dir, constants.KUBELET_SOCKET_NAME)

    # ------------------------------------------------------------- lifecycle

    def run(self) -> None:
        """Start everything and block until :meth:`shutdown` (or a signal
        handler calling it) fires.  ≙ dpm Manager.Run (dpm/manager.go:41-94)."""
        self.start()
        try:
            self._stop.wait()
        finally:
            self.stop_all()

    def start(self) -> None:
        # Capture the kubelet's identity BEFORE registering: if it restarts
        # mid-registration, the stale key makes the next reconcile register
        # again (conservative — at least one registration per incarnation).
        key = self._kubelet_key()
        self._start_and_register()
        self._registered_key = key
        self._reconciler = threading.Thread(
            target=self._reconcile_loop, name="tpu-reconcile", daemon=True
        )
        self._reconciler.start()
        if self._watch_kubelet:
            self._watcher = self._make_watcher()
            self._watcher.start()
            # Don't return until the watch is armed, or a kubelet restarting
            # immediately after our startup would go unnoticed.
            if not self._watcher.ready.wait(timeout=10):
                log.warning("socket watcher failed to arm within 10s")
        if self.pulse > 0:
            self._heartbeat = threading.Thread(
                target=self._heartbeat_loop, name="tpu-heartbeat", daemon=True
            )
            self._heartbeat.start()

    def shutdown(self) -> None:
        """Request an orderly exit of :meth:`run` (signal-handler safe)."""
        self._stop.set()

    def alive(self) -> bool:
        """Liveness (drives /healthz): not shut down and the recovery watcher
        thread is still running.  A stopped gRPC server while the kubelet is
        down is a NORMAL state (we restart on its return), not death — but a
        dead watcher means restarts would go unnoticed, which IS death."""
        if self._stop.is_set():
            return False
        if self._reconciler is not None and not self._reconciler.is_alive():
            # A dead reconciler means kubelet restarts would go unhandled.
            return False
        if not self._watch_kubelet:
            # An owning MultiResourceManager holds the watch; we're alive as
            # long as we haven't been stopped.
            return True
        return self._watcher is not None and self._watcher.is_alive()

    def stop_all(self) -> None:
        # Order matters: mark stopping FIRST so a concurrent watcher callback
        # (kubelet restarting at the same moment as our SIGTERM) cannot
        # resurrect the server after we tear it down.
        self._stop.set()
        self._reconcile_kick.set()  # unblock the reconciler so it can exit
        self.plugin.interrupt_streams()
        if self._watcher is not None:
            self._watcher.stop()
            self._watcher.join(timeout=5)
            self._watcher = None
        if self._reconciler is not None:
            self._reconciler.join(timeout=5)
            self._reconciler = None
        if self._heartbeat is not None:
            self._heartbeat.join(timeout=5)
            self._heartbeat = None
        self._stop_server()

    # --------------------------------------------------------------- serving

    def _start_server(self) -> None:
        """Idempotently bring the DevicePlugin server up on our unix socket."""
        with self._lock:
            if self._server is not None:
                return
            if self._stop.is_set():
                raise RuntimeError("manager is shutting down")
            # Remove a stale socket from a previous incarnation
            # (≙ dpm/plugin.go:96-99).
            try:
                os.unlink(self.socket_path)
            except FileNotFoundError:
                pass
            server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
            add_device_plugin_servicer(self.plugin, server)
            server.add_insecure_port(f"unix://{self.socket_path}")
            server.start()
            self._server = server
            log.info("DevicePlugin server listening on %s", self.socket_path)

    def _stop_server(self) -> None:
        self.plugin.interrupt_streams()
        with self._lock:
            if self._server is None:
                return
            self._server.stop(grace=1).wait()
            self._server = None
            try:
                os.unlink(self.socket_path)
            except FileNotFoundError:
                pass
            log.info("DevicePlugin server stopped")

    def _register(self) -> None:
        """Announce ourselves on the kubelet's Registration socket.

        A kubelet that rejects our API version is the first failure mode
        operators hit on version skew (the protocol says plugins must detect
        and handle it — reference api.proto:20-22 "terminate upon
        registration failure"; the reference dpm only logs the raw error,
        dpm/plugin.go:148-153).  We surface a dedicated operator-facing
        message and keep retrying with backoff from _start_and_register —
        the kubelet may be mid-upgrade and come back compatible.
        """
        try:
            # Cap connect backoff: C-core pools subchannels process-wide, so
            # failed dials against a flapping kubelet.sock otherwise push the
            # cached subchannel into exponential backoff (up to minutes) that
            # a FRESH channel to the same target inherits — turning the first
            # re-registration after an outage into a multi-second stall.
            with grpc.insecure_channel(
                f"unix://{self.kubelet_socket}",
                options=[
                    ("grpc.initial_reconnect_backoff_ms", 100),
                    ("grpc.max_reconnect_backoff_ms", 2000),
                ],
            ) as channel:
                RegistrationStub(channel).Register(
                    pb.RegisterRequest(
                        version=constants.VERSION,
                        endpoint=self.endpoint,
                        resource_name=self.resource,
                        options=pb.DevicePluginOptions(
                            pre_start_required=False,
                            get_preferred_allocation_available=True,
                        ),
                    ),
                    timeout=10,
                )
        except grpc.RpcError as e:
            detail = (e.details() or "") if hasattr(e, "details") else ""
            if "version" in detail.lower() or e.code() == grpc.StatusCode.INVALID_ARGUMENT:
                log.error(
                    "kubelet REJECTED registration of %s: %r — likely device-"
                    "plugin API version skew (we speak %s); upgrade the plugin "
                    "or the kubelet. Retrying with backoff in case the kubelet "
                    "is mid-upgrade.",
                    self.resource,
                    detail,
                    constants.VERSION,
                )
            raise
        self.registrations += 1
        self.plugin.metrics.registrations.inc()
        if self.plugin.flight is not None:
            self.plugin.flight.record(
                "registration", resource=self.resource, endpoint=self.endpoint
            )
        log.info("registered %s with kubelet (endpoint %s)", self.resource, self.endpoint)

    def _start_and_register(self) -> None:
        """Server-up + register, with retry; registration failure tears the
        server back down before the next attempt."""
        last_error: Exception | None = None
        for attempt in range(1, self._register_retries + 1):
            if self._stop.is_set():
                raise RuntimeError("manager is shutting down")
            try:
                self._start_server()
                self._register()
                return
            except Exception as e:  # noqa: BLE001 — retry any startup failure
                last_error = e
                log.warning(
                    "start/register attempt %d/%d failed: %s",
                    attempt,
                    self._register_retries,
                    e,
                )
                self._stop_server()
                # Linear backoff: 1×, 2×, 3×… the base delay — a rejecting
                # kubelet (version skew) shouldn't be hammered at a fixed
                # cadence, but must still be re-tried promptly once upgraded.
                if attempt < self._register_retries and not self._stop.wait(
                    self._register_retry_delay * attempt
                ):
                    continue
                break
        raise RuntimeError(
            f"could not register {self.resource} with kubelet at "
            f"{self.kubelet_socket}"
        ) from last_error

    # ------------------------------------------------------------- recovery

    def _make_watcher(self):
        from .watcher import KubeletSocketWatcher

        return KubeletSocketWatcher(
            self.plugin_dir,
            constants.KUBELET_SOCKET_NAME,
            on_create=self._on_kubelet_create,
            on_remove=self._on_kubelet_remove,
            poll_interval=self._watch_poll_interval,
        )

    def _kubelet_key(self) -> tuple | None:
        """Identity of the CURRENT kubelet.sock (None when absent).  A fresh
        kubelet incarnation binds a fresh socket → new inode; ctime guards
        against inode reuse on busy tmpfs."""
        try:
            st = os.stat(self.kubelet_socket)
            return (st.st_ino, st.st_ctime_ns)
        except OSError:
            return None

    def _on_kubelet_create(self) -> None:
        if not self._stop.is_set():
            self._reconcile_kick.set()

    _on_kubelet_remove = _on_kubelet_create

    def _reconcile_loop(self) -> None:
        """Drain kicks into reconciles.  Every watcher event (or fan-in call)
        just sets the kick; this loop then compares observed socket identity
        to the registered one — so a storm of N flaps while a reconcile is in
        flight coalesces into ONE pass against the final state, instead of N
        replayed restart/register dances against states that no longer exist."""
        retry: float | None = None
        while not self._stop.is_set():
            kicked = self._reconcile_kick.wait(timeout=retry)
            if self._stop.is_set():
                return
            if kicked:
                self._reconcile_kick.clear()
            retry = None if self._reconcile_once() else self._register_retry_delay

    def _reconcile_once(self) -> bool:
        """One level-triggered pass; returns False when it should be retried
        (a registration attempt failed against a live socket)."""
        key = self._kubelet_key()
        if key is None:
            # kubelet is down: stop serving until it returns (the create
            # event will kick us again).
            if self._registered_key is not None or self._server is not None:
                log.info("kubelet socket absent; stopping plugin server")
                if self.plugin.flight is not None:
                    self.plugin.flight.record("kubelet.absent")
                self._stop_server()
                self._registered_key = None
            return True
        if key == self._registered_key:
            return True  # already registered with this incarnation
        if key != self._counted_key:
            # Count kubelet INCARNATIONS, not reconcile attempts: a kubelet
            # that rejects registration re-enters here every retry tick and
            # must not inflate the restart metric.
            self._counted_key = key
            self.plugin.metrics.kubelet_restarts.inc()
            if self.plugin.flight is not None:
                self.plugin.flight.record("kubelet.restart")
        log.info("kubelet (re)start detected; re-registering")
        try:
            self._stop_server()
            self._start_and_register()
            self._registered_key = key
            return True
        except Exception:
            if self._stop.is_set():
                log.info("shutdown interrupted re-registration")
                return True
            log.exception(
                "re-registration after kubelet restart failed (will retry)"
            )
            return False

    # Public fan-in points for an owning MultiResourceManager (which holds
    # the single shared kubelet-socket watch; see resources.py).
    handle_kubelet_create = _on_kubelet_create
    handle_kubelet_remove = _on_kubelet_remove

    # ------------------------------------------------------------- heartbeat

    def _heartbeat_loop(self) -> None:
        log.info("health heartbeat every %.1fs", self.pulse)
        while not self._stop.wait(self.pulse):
            try:
                self.plugin.poll_once()
            except Exception:
                # Keep serving the last good snapshot, but meter the
                # failure: a steadily climbing counter with a quiet
                # device_updates series is how a wedged sysfs/devfs
                # surfaces on a dashboard before it pages.
                self.plugin.metrics.poll_failures.inc()
                log.exception("health poll failed")
